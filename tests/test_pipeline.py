"""Pipelined observed saturation (ISSUE 5): speculative round dispatch
with deferred frontier folds.

The invariant under test: a pipelined observed run is BYTE-IDENTICAL
per retired round (state + per-round derivation totals + round count)
to the synchronous depth-1 controller — the same step programs run in
the same order, only the host fetch is deferred — for depths 1/2/4,
with and without the adaptive sparse tail, including a forced
tier-interleave case.  Plus the accounting and telemetry properties:
speculative overshoot (the ≤depth-1 rounds dispatched past the fixed
point) is excluded from iteration/derivation accounting; the plain
non-adaptive observed path emits dense-tier ``FrontierStats`` so
serve's frontier gauges stay live with the sparse tail off; and the
controller's host gate-flag replication (``_host_gate_flags``) matches
the device fold (``_next_dirty``) on randomized masks and randomized
gate-reader structures."""

import numpy as np
import pytest

from distel_tpu.core.engine import SaturationEngine
from distel_tpu.core.indexing import index_ontology
from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine
from distel_tpu.frontend.normalizer import normalize
from distel_tpu.frontend.ontology_tools import (
    chain_tailed_ontology,
    synthetic_ontology,
)
from distel_tpu.owl import parser
from distel_tpu.runtime.instrumentation import FRONTIER_EVENTS


def _indexed(text):
    return index_ontology(normalize(parser.parse(text)))


@pytest.fixture(scope="module")
def galen_idx():
    """The PR 4 parity fixture: GALEN-shape corpus with a
    subclass-chain tail — late rounds derive one chain hop each, so
    the run has a long tail of cheap rounds for the pipeline (and the
    sparse tier) to work on."""
    return _indexed(chain_tailed_ontology(400, 12))


def _observed(idx, sparse, pipeline, **kw):
    engine = RowPackedSaturationEngine(idx, unroll=1, bucket=True, **kw)
    rounds = []
    res = engine.saturate_observed(
        observer=lambda it, d, ch: rounds.append((it, d, ch)),
        sparse_tail=sparse,
        pipeline=pipeline,
    )
    return engine, rounds, res


def _assert_same_closure(res_a, res_b):
    assert np.array_equal(
        np.asarray(res_a.packed_s), np.asarray(res_b.packed_s)
    )
    assert np.array_equal(
        np.asarray(res_a.packed_r), np.asarray(res_b.packed_r)
    )


# ------------------------------------------------ per-round parity


@pytest.mark.parametrize(
    "sparse",
    [{"enable": False}, True],
    ids=["plain", "sparse_tail"],
)
def test_pipelined_matches_sync_per_round(galen_idx, sparse):
    """THE parity pin: depths 1/2/4 produce identical per-round
    (iteration, derivations, changed) sequences, identical final
    closures and identical converged iteration counts to the
    synchronous controller — with and without the adaptive sparse
    tail."""
    _, sync_rounds, res_sync = _observed(
        galen_idx, sparse, {"enable": False}
    )
    for depth in (1, 2, 4):
        eng, rounds, res = _observed(
            galen_idx, sparse, {"enable": True, "depth": depth}
        )
        assert rounds == sync_rounds, depth
        assert res.iterations == res_sync.iterations, depth
        assert res.derivations == res_sync.derivations, depth
        _assert_same_closure(res, res_sync)
        # every retired round is recorded exactly once
        assert len(eng.frontier_rounds) == len(rounds), depth


def test_forced_tier_interleave_parity(galen_idx):
    """Interleave case: a mid threshold + a one-rung tiny workspace
    makes sparse-eligible rounds overflow back to dense, so the run
    interleaves speculative dense phases, sparse rounds and
    overflow-dense rounds — per-round parity and the final closure
    must still hold at depth 4."""
    cfg = {
        "density_threshold": 0.3,
        "hysteresis_rounds": 2,
        "capacity_buckets": 1,
        "capacity_floor": 8,
    }
    eng_s, sync_rounds, res_sync = _observed(
        galen_idx, cfg, {"enable": False}
    )
    eng_p, rounds, res = _observed(
        galen_idx, cfg, {"enable": True, "depth": 4}
    )
    assert rounds == sync_rounds
    assert res.iterations == res_sync.iterations
    _assert_same_closure(res, res_sync)
    tiers = [s.tier for s in eng_p.frontier_rounds]
    assert "sparse" in tiers and "dense" in tiers
    # the early dense phase actually ran speculatively
    assert any(s.inflight > 0 for s in eng_p.frontier_rounds)
    # the synchronous run never speculates
    assert all(s.inflight == 0 for s in eng_s.frontier_rounds)


# ------------------------------------- speculative overshoot accounting


def test_overshoot_excluded_from_accounting(galen_idx):
    """Converged pipelined results report the TRUE fixed-point round
    count: the ≤depth-1 rounds speculatively dispatched past
    convergence are fixed-point no-ops, dropped unretired — not
    retired rounds, not iterations, not derivations."""
    _, sync_rounds, res_sync = _observed(
        galen_idx, {"enable": False}, {"enable": False}
    )
    eng, rounds, res = _observed(
        galen_idx, {"enable": False}, {"enable": True, "depth": 4}
    )
    assert res.converged and res_sync.converged
    assert res.iterations == res_sync.iterations
    assert res.derivations == res_sync.derivations
    assert len(rounds) == len(sync_rounds)
    # pipelining engaged (so overshoot rounds WERE dispatched) ...
    assert any(s.inflight > 0 for s in eng.frontier_rounds)
    # ... and the recorded rounds end at the no-change round, with no
    # overshoot rounds after it
    assert eng.frontier_rounds[-1].iteration == res.iterations
    assert eng.frontier_rounds[-1].derivations == 0


def test_state_observer_forces_synchronous(galen_idx):
    """A ``state_observer`` receives live not-yet-donated round state —
    incompatible with speculation — so depth collapses to 1 and the
    snapshots still line up with the observer rounds."""
    engine = RowPackedSaturationEngine(galen_idx, unroll=1, bucket=True)
    seen = []
    res = engine.saturate_observed(
        state_observer=lambda it, d, ch, sp, rp: seen.append(
            (it, int(np.asarray(sp[0]).sum() >= 0))
        ),
        sparse_tail={"enable": False},
        pipeline={"enable": True, "depth": 4},
    )
    assert len(seen) == len(engine.frontier_rounds)
    assert all(s.inflight == 0 for s in engine.frontier_rounds)
    assert seen[-1][0] == res.iterations


def test_pipeline_cfg_validation(galen_idx):
    for bad in ({"depth": 0}, {"nope": 1}):
        with pytest.raises(ValueError):
            RowPackedSaturationEngine(
                galen_idx, unroll=1, bucket=True, pipeline=bad
            )


# ------------------------- plain-path FrontierStats (serve gauges)


def test_plain_observed_path_emits_frontier_stats(galen_idx):
    """With the sparse tail disabled the plain observed loop still
    emits per-round dense-tier FrontierStats (density pinned 1.0 — no
    frontier fold is measured there) into engine.frontier_rounds AND
    the process-global aggregate, so serve's frontier gauges don't go
    dark when ``sparse_tail.enable=false``."""
    before = FRONTIER_EVENTS.snapshot()
    eng, rounds, res = _observed(
        galen_idx, {"enable": False}, {"enable": True, "depth": 2}
    )
    after = FRONTIER_EVENTS.snapshot()
    assert eng.frontier_rounds, "plain path emitted no FrontierStats"
    assert all(s.tier == "dense" for s in eng.frontier_rounds)
    assert all(s.density == 1.0 for s in eng.frontier_rounds)
    assert all(
        s.rows_touched == s.total_rows == eng._sp_total_rows
        for s in eng.frontier_rounds
    )
    assert sum(s.derivations for s in eng.frontier_rounds) == res.derivations
    assert (
        after["dense_rounds"] - before["dense_rounds"]
        == len(eng.frontier_rounds)
    )
    # wall split present: wall is the blocking host time of the round
    assert all(
        abs(s.wall_s - (s.dispatch_s + s.retire_s)) < 1e-9
        for s in eng.frontier_rounds
    )


# --------------------- host gate flags vs the device _next_dirty fold


def test_host_gate_flags_matches_device_fold(galen_idx):
    """Property pin for the controller's host replication of the
    device gate fold: for random changed-S masks and any-R flags, the
    flags ``_host_gate_flags`` hands a dense round after sparse rounds
    must equal what ``_next_dirty`` would have folded on device from
    the same inputs."""
    import jax.numpy as jnp

    eng = RowPackedSaturationEngine(galen_idx, unroll=1, gate_chunks=True)
    assert eng._gate is not None, "fixture must build a gated engine"
    rng = np.random.default_rng(7)
    for trial in range(16):
        p = rng.choice([0.0, 0.002, 0.05, 0.5, 1.0])
        mask_s = rng.random(eng.nc) < p
        any_r = bool(rng.integers(2))
        host = eng._host_gate_flags(mask_s, any_r)
        dev = np.asarray(
            eng._next_dirty(jnp.asarray(mask_s), jnp.asarray(any_r), None)
        )
        assert np.array_equal(host, dev), (trial, p, any_r)


def test_host_gate_flags_matches_device_fold_random_readers(galen_idx):
    """Same property over RANDOMIZED gate-reader structures (kind mix,
    reader-row sets, flag order) — the reader shapes a real ontology
    happens to produce must not be the only covered ones."""
    import jax.numpy as jnp

    eng = RowPackedSaturationEngine(galen_idx, unroll=1, gate_chunks=True)
    rng = np.random.default_rng(11)
    orig = eng._gate
    try:
        for trial in range(12):
            readers = []
            for _ in range(int(rng.integers(1, 6))):
                kind = ["SR", "RR", "CR5"][int(rng.integers(3))]
                if kind == "SR":
                    k = int(rng.integers(0, 6))
                    rows = np.sort(
                        rng.choice(eng.nc, size=k, replace=False)
                    ).astype(np.int64)
                    readers.append(("SR", rows))
                else:
                    readers.append((kind, None))
            eng._gate = {"readers": readers, "n_flags": len(readers)}
            mask_s = rng.random(eng.nc) < rng.choice([0.003, 0.2])
            any_r = bool(rng.integers(2))
            host = eng._host_gate_flags(mask_s, any_r)
            dev = np.asarray(
                eng._next_dirty(
                    jnp.asarray(mask_s), jnp.asarray(any_r), None
                )
            )
            assert np.array_equal(host, dev), (trial, readers)
    finally:
        eng._gate = orig


# ------------------------------- dense engine's pipelined observed loop


def test_dense_engine_pipelined_observed_matches():
    """engine.py's observed_loop grew the same deferred-retire
    structure: the dense SaturationEngine at pipeline_depth=3 retires
    the identical round sequence and closure as the synchronous run."""
    text = synthetic_ontology(
        n_classes=160, n_anatomy=16, n_locations=14, n_definitions=8,
    )
    text += "\n" + "\n".join(
        f"SubClassOf(DTail{i} DTail{i + 1})" for i in range(8)
    )
    text += "\nSubClassOf(Class0 DTail0)"
    idx = _indexed(text)

    def run(depth):
        eng = SaturationEngine(idx, unroll=1)
        rounds = []
        res = eng.saturate_observed(
            observer=lambda it, d, ch: rounds.append((it, d, ch)),
            pipeline_depth=depth,
        )
        return rounds, res

    rounds_sync, res_sync = run(1)
    rounds_pipe, res_pipe = run(3)
    assert rounds_pipe == rounds_sync
    assert res_pipe.iterations == res_sync.iterations
    assert res_pipe.derivations == res_sync.derivations
    assert res_pipe.subsumer_dict() == res_sync.subsumer_dict()
