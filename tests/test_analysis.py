"""distel-lint: per-rule must-fire / must-not-fire fixtures, baseline
round-trip, repo self-lint, and the runtime lockdep counterpart.

Each rule gets a pair of synthetic modules: one seeded with exactly
the violation it exists to catch, one exercising the legitimate idiom
the rule must NOT flag (the guarded non-bucketed fallback, the
"caller holds" docstring convention, try-acquire, RLock reentrancy).
The repo self-lint test is the contract the CI gate enforces: the
committed baseline covers everything the rules currently find, every
entry justified.
"""

import json
import threading

import pytest

from distel_tpu.analysis import knobs, lockorder, metricnames, purity, sharedstate
from distel_tpu.analysis.findings import Baseline, Finding
from distel_tpu.analysis.project import Project
from distel_tpu.analysis.runner import (
    DEFAULT_INCLUDE,
    repo_root,
    run_rules,
)
from distel_tpu.testing import lockdep


def project(files):
    return Project("/synthetic", files=files)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# --------------------------------------------------------------------
# rule 1: lock order
# --------------------------------------------------------------------

_LOCK_CYCLE = '''
import threading

class A:
    def __init__(self):
        self._lock = threading.Lock()
        self.peer = None

    def hot(self):
        with self._lock:
            self.peer.poke()

class B:
    def __init__(self):
        self._lock = threading.Lock()
        self.owner = None

    def poke(self):
        with self._lock:
            pass

    def back(self):
        with self._lock:
            self.owner.hot()
'''

_LOCK_CLEAN = '''
import threading

class A:
    def __init__(self):
        self._lock = threading.Lock()

    def seq(self):
        with self._lock:
            x = 1
        with self._lock:
            return x

    def try_acquire(self, other):
        # non-blocking acquire cannot deadlock: no ordering edge
        if other.lock.acquire(blocking=False):
            try:
                pass
            finally:
                other.lock.release()

class Entry:
    def __init__(self):
        self.lock = threading.Lock()
'''


def test_lockorder_cycle_fires():
    fs = lockorder.check(project({"pkg/a.py": _LOCK_CYCLE}))
    assert any(f.rule == lockorder.RULE_CYCLE for f in fs), fs
    cyc = [f for f in fs if f.rule == lockorder.RULE_CYCLE][0]
    assert "A._lock" in cyc.symbol and "B._lock" in cyc.symbol


def test_lockorder_clean_is_silent():
    fs = lockorder.check(project({"pkg/a.py": _LOCK_CLEAN}))
    assert [f for f in fs if f.rule == lockorder.RULE_CYCLE] == []
    assert [f for f in fs if f.rule == lockorder.RULE_CROSS] == []


def test_lockorder_cross_module_edge():
    held = '''
import threading
from pkg.leaf import Leaf

class Holder:
    def __init__(self):
        self._lock = threading.Lock()
        self.leaf = Leaf()

    def work(self):
        with self._lock:
            self.leaf.bump()
'''
    leaf = '''
import threading

class Leaf:
    def __init__(self):
        self._lock = threading.Lock()

    def bump(self):
        with self._lock:
            pass
'''
    fs = lockorder.check(
        project({"pkg/holder.py": held, "pkg/leaf.py": leaf})
    )
    cross = [f for f in fs if f.rule == lockorder.RULE_CROSS]
    assert len(cross) == 1
    assert cross[0].symbol == "Holder._lock -> Leaf._lock"


def test_lockorder_caller_holds_docstring():
    src = '''
import threading

class R:
    def __init__(self):
        self._lock = threading.Lock()
        self.other = None

    def helper(self):
        """Caller holds ``self._lock``."""
        with self.other.wrap_lock:
            pass
'''
    other = '''
import threading

class W:
    def __init__(self):
        self.wrap_lock = threading.Lock()
'''
    fs = lockorder.check(
        project({"pkg/r.py": src, "pkg/w.py": other})
    )
    # helper's body nests W.wrap_lock under the documented R._lock —
    # a cross-module acquire-while-holding the docstring made visible
    assert any(
        f.rule == lockorder.RULE_CROSS
        and f.symbol == "R._lock -> W.wrap_lock"
        for f in fs
    ), fs


# --------------------------------------------------------------------
# rule 2: traced purity
# --------------------------------------------------------------------

_PURE_BAD = '''
import jax
import jax.numpy as jnp
import numpy as np

class Engine:
    def __init__(self, idx):
        self._table = jnp.asarray(idx.table)
        self._step_jit = jax.jit(lambda s: self._step(s))

    def _step(self, s):
        t = self._table            # closure-captured ontology array
        total = float(jnp.sum(s))  # host sync inside the trace
        if total > 0:              # python branch on a traced value
            s = s + t
        host = np.asarray(s)       # device->host inside the trace
        return s
'''

_PURE_OK = '''
import jax
import jax.numpy as jnp

class Engine:
    def __init__(self, idx, bucket):
        self._bucket = bucket
        self._table = jnp.asarray(idx.table)
        self._step_jit = jax.jit(lambda s, masks: self._step(s, masks))

    def _step(self, s, masks=None):
        # the documented non-bucketed fallback: guarded self-read
        mk = self._table if masks is None else masks
        if self._bucket:
            mk = masks["table"]
        n = s.shape[0]          # static metadata, launders taint
        if n > 4:               # branch on static shape: fine
            s = s + mk
        plan = self._plan(s.shape[0])
        if "extra" in masks:    # pytree-structure membership: fine
            s = s + masks["extra"]
        return jnp.where(s > 0, s, 0)

    def _plan(self, n):
        # trace-time host helper called with STATIC args only
        if n > 128:
            return "big"
        return "small"

    def controller(self, s):
        # NOT reached from a jit root: host-side folds are legitimate
        return float(jnp.sum(s))
'''


def test_purity_fires_on_all_three():
    fs = purity.check(project({"pkg/eng.py": _PURE_BAD}))
    got = rules_of(fs)
    assert purity.RULE_CAPTURE in got, fs
    assert purity.RULE_SYNC in got, fs
    assert purity.RULE_BRANCH in got, fs


def test_purity_guarded_fallback_and_controller_are_silent():
    fs = purity.check(project({"pkg/eng.py": _PURE_OK}))
    assert fs == [], [f.render() for f in fs]


def test_purity_root_called_by_root_keeps_static_argnums():
    """A jit root reached first as another root's callee must keep its
    static_argnums — otherwise its static param reads as tainted and
    legitimate Python branches on it fire bogus findings."""
    src = '''
import jax
import jax.numpy as jnp

class E:
    def __init__(self):
        self._a = jax.jit(self._outer)
        self._b = jax.jit(self._kern, static_argnums=(2,))

    def _outer(self, x):
        return self._kern(x, x, 4)

    def _kern(self, x, y, n):
        if n > 2:          # static argnum: must stay silent
            x = x + y
        return x
'''
    fs = purity.check(project({"pkg/e.py": src}))
    assert not any(f.rule == purity.RULE_BRANCH for f in fs), [
        f.render() for f in fs
    ]


def test_lockorder_bare_acquire_in_with_body_scopes_correctly():
    """A bare .acquire() inside a with-body outlives the with; the
    with-exit must pop ITS lock, not the acquired one — a positional
    pop would leave the with-lock spuriously held and fabricate an
    edge to the next acquisition."""
    src = '''
import threading

class A:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def work(self):
        with self._a:
            self._b.acquire()
        with self._c:
            pass
        self._b.release()
'''
    fs = lockorder.check(project({"pkg/a.py": src}))
    facts = lockorder._collect_facts(
        Project("/x", files={"pkg/a.py": src}), ["pkg/a.py"]
    )
    edges = {(e.held, e.acquired) for e in facts["A.work"].edges}
    assert ("A._a", "A._b") in edges          # real nesting
    assert ("A._b", "A._c") in edges          # _b held past the with
    assert ("A._a", "A._c") not in edges      # _a was released


@pytest.mark.no_lockdep
def test_lockdep_cross_test_edge_accumulation():
    """check() consumes violations but KEEPS edges: an A->B from one
    armed test plus a B->A from a later one is still an inversion."""
    lockdep.enable()
    try:
        lockdep.reset()
        a = threading.Lock()
        b = threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        t = threading.Thread(target=ab)
        t.start(); t.join()
        lockdep.check()          # test 1 passes, edge a->b kept
        assert lockdep.edges()   # edges survived the check

        def ba():
            with b:
                with a:
                    pass

        t = threading.Thread(target=ba)
        t.start(); t.join()
        with pytest.raises(lockdep.LockOrderViolation):
            lockdep.check()      # test 2 closes the cycle
        lockdep.check()          # violations were consumed by the raise
    finally:
        lockdep.disable()
        lockdep.reset()


def test_purity_partial_jit_decorator_is_a_root():
    src = '''
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnums=(1,))
def kernel(x, n):
    if n > 2:              # static argnum: fine
        x = x * 2
    s = float(jnp.sum(x))  # host sync on the traced arg
    return x
'''
    fs = purity.check(project({"pkg/k.py": src}))
    assert any(f.rule == purity.RULE_SYNC for f in fs), fs
    assert not any(f.rule == purity.RULE_BRANCH for f in fs), fs


# --------------------------------------------------------------------
# rule 3: shared state
# --------------------------------------------------------------------

_SHARED_BAD = '''
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def sneak(self, k):
        self._items.pop(k, None)   # mutation outside the lock
'''

_SHARED_OK = '''
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def _drop(self, k):
        """Caller holds ``self._lock``."""
        self._items.pop(k, None)
'''


def test_sharedstate_fires():
    fs = sharedstate.check(project({"pkg/s.py": _SHARED_BAD}))
    assert any(
        f.rule == sharedstate.RULE and f.symbol == "Store._items"
        for f in fs
    ), fs


def test_sharedstate_docstring_convention_is_silent():
    fs = sharedstate.check(project({"pkg/s.py": _SHARED_OK}))
    assert fs == [], [f.render() for f in fs]


def test_holds_docstring_survives_line_wrap():
    """The load-bearing "Caller holds ..." sentence wraps across
    docstring lines in real code (registry._spill) — the shared parser
    must normalize whitespace, and must NOT leak tokens from later
    sentences into the holds set."""
    import ast as _ast

    from distel_tpu.analysis.project import caller_holds_tokens

    src = '''
def helper(entry):
    """Snapshot the entry's closure and drop the classifier.  Caller
    holds ``entry.lock``.  Unrelated tail prose mentioning
    other.lock must not count."""
'''
    fn = _ast.parse(src).body[0]
    assert caller_holds_tokens(fn) == ["entry.lock"]


# --------------------------------------------------------------------
# rule 4: config knobs
# --------------------------------------------------------------------

_KNOB_CONFIG = '''
from dataclasses import dataclass

@dataclass
class ClassifierConfig:
    used_knob: int = 1
    dead_knob: int = 2
    undocumented_knob: int = 3

    @classmethod
    def from_properties(cls, path):
        raw = {}
        cfg = cls()
        if "used.knob" in raw:
            cfg.used_knob = int(raw["used.knob"])
        if "undocumented.knob" in raw:
            cfg.undocumented_knob = int(raw["undocumented.knob"])
        if "ghost.knob" in raw:
            cfg.gohst_knob = int(raw["ghost.knob"])
        return cfg
'''

_KNOB_USER = '''
def use(cfg):
    return cfg.used_knob + cfg.undocumented_knob
'''

_KNOB_README = "options: `used.knob` does things.\n"


def _knob_findings():
    p = Project(
        "/synthetic",
        files={
            "distel_tpu/config.py": _KNOB_CONFIG,
            "distel_tpu/user.py": _KNOB_USER,
        },
    )
    return knobs.check(p, _KNOB_README)


def test_knob_dead():
    fs = _knob_findings()
    assert any(
        f.rule == knobs.RULE_DEAD and f.symbol == "dead_knob" for f in fs
    ), fs
    # read knobs are not dead
    assert not any(
        f.rule == knobs.RULE_DEAD and f.symbol == "used_knob" for f in fs
    )


def test_knob_undocumented():
    fs = _knob_findings()
    assert any(
        f.rule == knobs.RULE_UNDOC and f.symbol == "undocumented.knob"
        for f in fs
    ), fs
    assert not any(
        f.rule == knobs.RULE_UNDOC and f.symbol == "used.knob" for f in fs
    )


def test_knob_misspelled():
    fs = _knob_findings()
    # `cfg.gohst_knob` typo: the key parses, nothing real is set
    assert any(
        f.rule == knobs.RULE_MISSPELLED and "ghost.knob" in f.symbol
        for f in fs
    ), fs


# --------------------------------------------------------------------
# rule 5: metric names
# --------------------------------------------------------------------

_METRIC_SRC = '''
class App:
    def __init__(self, metrics):
        metrics.counter_inc("distel_good_events_total")
        metrics.counter_inc("distel_bad_events")          # counter sans _total
        metrics.gauge_set("distel_depth")
        metrics.gauge_set("distel_bad_depth_total")       # gauge with _total
        metrics.observe("distel_wait_seconds", 1.0)
'''

_METRIC_README = (
    "| `distel_good_events_total` | good |\n"
    "| `distel_depth` | depth |\n"
    "| `distel_wait_seconds` | wait |\n"
    "| `distel_bad_events` | bad |\n"
    "| `distel_bad_depth_total` | bad |\n"
    "| `distel_ghost_family_total` | documented but never minted |\n"
)


def test_metric_naming_discipline():
    p = Project(
        "/synthetic", files={"distel_tpu/app.py": _METRIC_SRC}
    )
    fs = metricnames.check(p, _METRIC_README)
    by_sym = {f.symbol: f for f in fs if f.rule == metricnames.RULE_NAME}
    assert "distel_bad_events" in by_sym, fs
    assert "distel_bad_depth_total" in by_sym, fs
    assert "distel_good_events_total" not in by_sym
    assert "distel_wait_seconds" not in by_sym


def test_metric_readme_both_directions():
    p = Project(
        "/synthetic", files={"distel_tpu/app.py": _METRIC_SRC}
    )
    fs = metricnames.check(p, _METRIC_README)
    stale = [
        f for f in fs
        if f.rule == metricnames.RULE_README
        and f.symbol == "distel_ghost_family_total"
    ]
    assert stale, fs
    # a family missing from README fires the other direction
    fs2 = metricnames.check(p, "| `distel_good_events_total` | g |\n")
    assert any(
        f.rule == metricnames.RULE_README and f.symbol == "distel_depth"
        for f in fs2
    ), fs2


def test_metric_brace_and_wildcard_coverage():
    src = '''
class App:
    def __init__(self, m):
        m.gauge_set("distel_frontier_dense_rounds")
        m.gauge_set("distel_frontier_sparse_rounds")
        m.counter_inc("distel_registry_evictions_total")
'''
    readme = (
        "`distel_frontier_{dense,sparse}_rounds` and "
        "`distel_registry_*` cover everything\n"
    )
    p = Project("/synthetic", files={"distel_tpu/app.py": src})
    fs = metricnames.check(p, readme)
    assert [f for f in fs if f.rule == metricnames.RULE_README] == [], fs


# --------------------------------------------------------------------
# baseline round-trip
# --------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    f1 = Finding("shared-state", "pkg/s.py", 12, "Store._items", "msg")
    f2 = Finding("knob-dead", "config.py", 3, "dead_knob", "msg2")

    # add: both findings suppressed once baselined with justification
    bl = Baseline.from_findings([f1, f2], justification="pre-existing")
    path = tmp_path / "baseline.json"
    bl.save(str(path))
    loaded = Baseline.load(str(path))
    fresh, suppressed, stale = loaded.filter([f1, f2])
    assert fresh == [] and len(suppressed) == 2 and stale == []

    # suppress: fixing one finding leaves its entry stale
    fresh, suppressed, stale = loaded.filter([f1])
    assert fresh == [] and len(stale) == 1

    # regression: a NEW finding re-fires even with the baseline loaded
    f3 = Finding("shared-state", "pkg/s.py", 40, "Store._other", "msg")
    fresh, _, _ = loaded.filter([f1, f3])
    assert [f.symbol for f in fresh] == ["Store._other"]

    # line drift does NOT re-fire (fingerprint excludes the line)
    drifted = Finding("shared-state", "pkg/s.py", 99, "Store._items", "msg")
    fresh, suppressed, _ = loaded.filter([drifted])
    assert fresh == [] and len(suppressed) == 1

    # unjustified entries are flagged
    bl2 = Baseline.from_findings([f1])
    assert bl2.unjustified() == [f1.fingerprint()]


# --------------------------------------------------------------------
# repo self-lint: the CI contract
# --------------------------------------------------------------------

def test_repo_lint_is_clean_under_committed_baseline():
    root = repo_root()
    p = Project(root, include=DEFAULT_INCLUDE)
    with open(root + "/README.md", encoding="utf-8") as f:
        readme = f.read()
    findings = run_rules(p, readme)
    bl = Baseline.load(root + "/.distel-lint-baseline.json")
    fresh, _suppressed, stale = bl.filter(findings)
    assert fresh == [], "\n".join(f.render() for f in fresh)
    assert stale == [], f"stale baseline entries: {stale}"
    assert bl.unjustified() == []


def test_cli_lint_fails_on_fresh_finding(tmp_path, capsys):
    """The CI gate's contract end-to-end: a tree with a non-baselined
    finding exits 1 and reports it; baselining it (justified) exits 0;
    an unjustified baseline exits 1."""
    from distel_tpu.cli import main

    root = tmp_path / "repo"
    # under serve/ so the lock rules' scope covers it
    (root / "distel_tpu" / "serve").mkdir(parents=True)
    (root / "distel_tpu" / "serve" / "bad.py").write_text(_SHARED_BAD)
    (root / "README.md").write_text("")

    json_out = tmp_path / "findings.json"
    rc = main([
        "lint", "--root", str(root), "--json", str(json_out),
    ])
    assert rc == 1
    doc = json.loads(json_out.read_text())
    assert any(
        f["rule"] == "shared-state" for f in doc["fresh"]
    ), doc

    # write + justify a baseline → clean exit
    bl_path = tmp_path / "bl.json"
    rc = main([
        "lint", "--root", str(root),
        "--write-baseline", str(bl_path),
    ])
    assert rc == 0
    bl_doc = json.loads(bl_path.read_text())
    for rec in bl_doc["findings"].values():
        rec["justification"] = "fixture debt"
    bl_path.write_text(json.dumps(bl_doc))
    rc = main([
        "lint", "--root", str(root), "--baseline", str(bl_path),
    ])
    assert rc == 0

    # unjustified baseline entries fail the run
    for rec in bl_doc["findings"].values():
        rec["justification"] = ""
    bl_path.write_text(json.dumps(bl_doc))
    rc = main([
        "lint", "--root", str(root), "--baseline", str(bl_path),
    ])
    assert rc == 1
    capsys.readouterr()


# --------------------------------------------------------------------
# runtime lockdep
# --------------------------------------------------------------------

@pytest.mark.no_lockdep
def test_lockdep_detects_inversion_without_deadlock():
    """The seeded ABBA repro: two threads take two locks in opposite
    orders but NEVER overlap (joined sequentially) — no deadlock
    happens, the inversion is still reported."""
    lockdep.enable()
    try:
        lockdep.reset()
        a = threading.Lock()
        b = threading.Lock()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        for fn in (ab, ba):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        with pytest.raises(lockdep.LockOrderViolation) as exc:
            lockdep.check()
        assert "inversion" in str(exc.value)
    finally:
        lockdep.disable()
        lockdep.reset()


@pytest.mark.no_lockdep
def test_lockdep_clean_patterns_pass():
    lockdep.enable()
    try:
        lockdep.reset()
        a = threading.Lock()
        b = threading.Lock()
        # consistent order on both threads
        def ordered():
            with a:
                with b:
                    pass
        for _ in range(2):
            t = threading.Thread(target=ordered)
            t.start()
            t.join()
        # RLock reentrancy is not same-class nesting
        r = threading.RLock()
        with r:
            with r:
                pass
        # try-acquire records no ordering edge
        l2 = threading.Lock()
        with b:
            assert l2.acquire(blocking=False)
            l2.release()
        lockdep.check()
        assert all(e != ("b", "a") for e in lockdep.edges())
    finally:
        lockdep.disable()
        lockdep.reset()


@pytest.mark.no_lockdep
def test_lockdep_condition_wait_releases_bookkeeping():
    """Condition.wait drops the lock: the waiter must not appear to
    hold it while the notifier runs its own nested acquisitions."""
    lockdep.enable()
    try:
        lockdep.reset()
        cv = threading.Condition()
        inner = threading.Lock()
        woke = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                woke.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        import time

        time.sleep(0.2)
        with inner:          # inner -> cv on the notifier
            with cv:
                cv.notify_all()
        t.join()
        assert woke
        # the waiter re-acquired cv AFTER wait without holding inner:
        # no cv -> inner edge exists, so no inversion
        lockdep.check()
    finally:
        lockdep.disable()
        lockdep.reset()


def test_lockdep_fixture_is_armed_for_concurrency_suites():
    """Assert the conftest wiring constant so a test-module rename
    doesn't silently disarm the lockdep guard."""
    import conftest

    assert set(conftest._LOCKDEP_MODULES) == {
        "test_serve_concurrency",
        "test_fleet",
    }
    # and this module itself runs un-armed (the seeded-inversion tests
    # above would otherwise trip the fixture's check())
    assert lockdep.enabled() is False
