"""Sharded adaptive controller parity (ISSUE 15): the sparse-tail +
pipelined controller under shard_map.

The soundness claim under test extends tests/test_sparse_tail.py's to
the mesh path: a SHARDED adaptive run — dense rounds through the
shard_map-structured observe program, sparse rounds through the
shard_map-structured compacted program, speculative dispatch at any
pipeline depth — retires a per-round (iteration, derivations, changed)
sequence BYTE-IDENTICAL to the single-device adaptive controller's,
and lands byte-identical final closures.  That holds because the
sparse program's body is the single shared ``_sparse_exec`` (the mesh
build only narrows state to the shard-local word window and psum-folds
the round's frontier ONCE at the end), and the controller's host logic
never branches on the mesh.

Also pinned: the compat shim resolves on this pin (these tests would
read as the old skips otherwise) and the sharded sparse program
actually runs the sparse tier (not a silent dense fallback).
"""

import numpy as np
import pytest

from distel_tpu.core.engine import fetch_global
from distel_tpu.core.indexing import index_ontology
from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine
from distel_tpu.frontend.normalizer import normalize
from distel_tpu.frontend.ontology_tools import chain_tailed_ontology
from distel_tpu.owl import parser

from sharding_support import requires_shard_map


@pytest.fixture(scope="module")
def galen_idx():
    """Chain-tailed GALEN shape (the sparse tier's regime), sized so a
    2-shard word axis still holds multiple words per shard.  The
    DisjointClasses axiom makes the chain's midsection unsatisfiable
    (TailChain3 ⊑ … ⊑ TailChain7 ⊓ ¬TailChain7), so the engines build
    with ⊥ present and the sharded sparse program's CR5 branch — the
    masked-local-extract + psum exchange inside a ``lax.cond`` — is
    actually traced and exercised by every parity assertion below
    (without it no corpus in the suite reaches that code)."""
    text = chain_tailed_ontology(400, 12)
    text += "\nDisjointClasses(TailChain3 TailChain7)"
    return index_ontology(normalize(parser.parse(text)))


@pytest.fixture(scope="module")
def mesh2():
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices (see conftest.py)")
    return jax.sharding.Mesh(np.array(jax.devices()[:2]), ("c",))


def _observed(idx, mesh, sparse, depth):
    engine = RowPackedSaturationEngine(
        idx, unroll=1, bucket=True, mesh=mesh
    )
    rounds = []
    res = engine.saturate_observed(
        observer=lambda it, d, ch: rounds.append((it, d, ch)),
        sparse_tail=sparse,
        pipeline={"enable": depth > 1, "depth": depth},
    )
    return engine, rounds, res


def _closure(res):
    return tuple(
        np.asarray(a)
        for a in fetch_global((res.packed_s, res.packed_r))
    )


#: forces every post-warmup round sparse — the strictest exercise of
#: the sharded selection/compaction path (same knob the single-device
#: parity fixture uses)
_ALL_SPARSE = {"density_threshold": 1.1, "hysteresis_rounds": 1}


@requires_shard_map
def test_sharded_adaptive_dense_only_matches_local(galen_idx, mesh2):
    """Dense-only (sparse tail off) sharded adaptive vs single-device:
    identical retired round sequence and closures at the default
    pipeline depth."""
    _, lr, res_l = _observed(galen_idx, None, {"enable": False}, 2)
    _, sr, res_s = _observed(galen_idx, mesh2, {"enable": False}, 2)
    assert sr == lr
    cl, cs = _closure(res_l), _closure(res_s)
    assert np.array_equal(cl[0], cs[0]) and np.array_equal(cl[1], cs[1])
    # the fixture's disjointness really fired: ⊥ propagation (CR5) is
    # live in every run this module compares
    assert res_s.unsatisfiable()


@requires_shard_map
def test_sharded_sparse_interleave_matches_local(galen_idx, mesh2):
    """Sparse-tail interleave: the sharded controller must RUN the
    sparse tier (not silently fall back dense) and still retire the
    single-device adaptive sequence byte-for-byte."""
    el, lr, res_l = _observed(galen_idx, None, _ALL_SPARSE, 1)
    es, sr, res_s = _observed(galen_idx, mesh2, _ALL_SPARSE, 1)
    assert sr == lr
    cl, cs = _closure(res_l), _closure(res_s)
    assert np.array_equal(cl[0], cs[0]) and np.array_equal(cl[1], cs[1])
    tiers_l = [s.tier for s in el.frontier_rounds]
    tiers_s = [s.tier for s in es.frontier_rounds]
    # depth 1 drains between every round: tier decisions see the same
    # frontier on both paths and must agree round for round
    assert tiers_s == tiers_l
    assert tiers_s.count("sparse") >= 3


@requires_shard_map
@pytest.mark.parametrize("depth", [1, 2, 4])
def test_sharded_pipeline_depths_match_local(galen_idx, mesh2, depth):
    """Pipeline depths 1/2/4: speculative dispatch on the mesh path
    must retire the same rounds as the single-device controller AT THE
    SAME DEPTH (the drain-before-tier-switch slack may shift WHICH
    rounds run sparse across depths — never what any round derives)."""
    _, lr, res_l = _observed(galen_idx, None, _ALL_SPARSE, depth)
    _, sr, res_s = _observed(galen_idx, mesh2, _ALL_SPARSE, depth)
    assert sr == lr
    cl, cs = _closure(res_l), _closure(res_s)
    assert np.array_equal(cl[0], cs[0]) and np.array_equal(cl[1], cs[1])
    # the dense-only reference: every depth's retired sequence is the
    # synchronous dense loop's (the adaptive + pipelined machinery is
    # observability-neutral end to end)
    _, dr, _res_d = _observed(galen_idx, None, {"enable": False}, 1)
    assert sr == dr


@requires_shard_map
def test_sharded_sparse_program_is_sharded(galen_idx, mesh2):
    """The sparse program's state outputs stay word-axis sharded (the
    round must not silently gather to one device and re-scatter)."""
    engine = RowPackedSaturationEngine(
        idx := galen_idx, unroll=1, bucket=True, mesh=mesh2
    )
    res = engine.saturate_observed(sparse_tail=_ALL_SPARSE)
    assert any(s.tier == "sparse" for s in engine.frontier_rounds)
    assert len(res.packed_s.sharding.device_set) == 2
    shard_cols = {sh.data.shape[1] for sh in res.packed_s.addressable_shards}
    assert shard_cols == {engine.wc // 2}
    assert idx.n_concepts  # fixture sanity
