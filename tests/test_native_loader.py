"""Native C++ load plane vs the Python reference frontend.

Both paths (text → IndexedOntology) must yield the *same closure* — ids
may differ, so equivalence is checked on per-name subsumer sets after
saturation, plus oracle agreement (three-way differential)."""

import numpy as np
import pytest

from distel_tpu.core.engine import SaturationEngine
from distel_tpu.core.indexing import index_ontology
from distel_tpu.frontend.normalizer import normalize
from distel_tpu.owl import parser
from distel_tpu.owl import native_loader
from distel_tpu.testing.differential import diff_engine_vs_oracle

pytestmark = pytest.mark.skipif(
    not native_loader.native_available(), reason="native library not built"
)


def _subsumers_by_name(idx, result):
    out = {}
    n = idx.n_concepts
    for name, cid in idx.concept_ids.items():
        if name.startswith(("distel:gensym#", "distel:aux#")):
            continue
        sups = {
            idx.concept_names[j]
            for j in np.nonzero(result.s[cid, :n])[0]
            if not idx.concept_names[j].startswith(("distel:gensym#", "distel:aux#"))
        }
        out[name] = sups
    return out


def assert_equivalent(text):
    idx_native = native_loader.load_indexed(text)
    res_native = SaturationEngine(idx_native).saturate()

    norm = normalize(parser.parse(text))
    idx_py = index_ontology(norm)
    res_py = SaturationEngine(idx_py).saturate()

    a = _subsumers_by_name(idx_native, res_native)
    b = _subsumers_by_name(idx_py, res_py)
    assert a == b, {
        k: (a.get(k), b.get(k)) for k in set(a) | set(b) if a.get(k) != b.get(k)
    }
    # and against the oracle
    report = diff_engine_vs_oracle(norm, res_py)
    assert report.ok(), report.summary()


CASES = [
    "SubClassOf(A B)\nSubClassOf(B C)",
    "SubClassOf(ObjectIntersectionOf(A B C) D)\nSubClassOf(X A)\nSubClassOf(X B)\nSubClassOf(X C)",
    (
        "TransitiveObjectProperty(p)\n"
        "SubClassOf(A ObjectSomeValuesFrom(p B))\n"
        "SubClassOf(B ObjectSomeValuesFrom(p D))\n"
        "SubClassOf(ObjectSomeValuesFrom(p D) E)"
    ),
    (
        "SubObjectPropertyOf(ObjectPropertyChain(r s) t)\n"
        "SubObjectPropertyOf(t u)\n"
        "SubClassOf(A ObjectSomeValuesFrom(r B))\n"
        "SubClassOf(B ObjectSomeValuesFrom(s D))\n"
        "SubClassOf(ObjectSomeValuesFrom(u D) E)"
    ),
    (
        "ObjectPropertyDomain(r D)\nObjectPropertyRange(r E)\n"
        "SubObjectPropertyOf(q r)\n"
        "SubClassOf(A ObjectSomeValuesFrom(q B))\n"
        "SubClassOf(ObjectSomeValuesFrom(r E) F)"
    ),
    "DisjointClasses(A B C)\nSubClassOf(X A)\nSubClassOf(X B)",
    (
        "SubClassOf(A ObjectSomeValuesFrom(r ObjectIntersectionOf(B "
        "ObjectSomeValuesFrom(s C))))\n"
        "SubClassOf(ObjectSomeValuesFrom(r B) D)"
    ),
    (
        "Prefix(:=<http://x#>)\nOntology(<http://x>\n"
        "Declaration(Class(:A))\nDeclaration(NamedIndividual(:a))\n"
        "Declaration(NamedIndividual(:b))\n"
        'AnnotationAssertion(rdfs:label :A "label")\n'
        "ClassAssertion(:A :a)\nObjectPropertyAssertion(:r :a :b)\n"
        "SubClassOf(ObjectSomeValuesFrom(:r owl:Thing) :HasR)\n)"
    ),
    "SubClassOf(A ObjectUnionOf(B C))\nSubClassOf(A D)\nHasKey(A () (p))",
    "SubObjectPropertyOf(ObjectPropertyChain(p q r) s)\n"
    "SubClassOf(A ObjectSomeValuesFrom(p B))\n"
    "SubClassOf(B ObjectSomeValuesFrom(q C))\n"
    "SubClassOf(C ObjectSomeValuesFrom(r D))\n"
    "SubClassOf(ObjectSomeValuesFrom(s D) E)",
    "EquivalentClasses(A ObjectIntersectionOf(B ObjectSomeValuesFrom(r C)))\n"
    "SubClassOf(X B)\nSubClassOf(X ObjectSomeValuesFrom(r C))",
    # ObjectHasValue ≡ ∃r.{a} on both sides (regression: the native
    # parser dropped it as non-EL while the Python parser desugared it)
    "SubClassOf(Cat ObjectHasValue(owns felix))\n"
    "SubClassOf(ObjectHasValue(owns felix) PetOwner)\n"
    "SubClassOf(ObjectSomeValuesFrom(owns ObjectOneOf(felix)) PetOwner2)\n"
    "SubClassOf(PetOwner Person)",
    # datatypes-as-classes (reference EntityType.DATATYPE): named
    # datatypes behave as classes, DataHasValue keys on the literal's
    # datatype, complex data ranges drop out of profile
    "SubClassOf(Person DataSomeValuesFrom(hasName xsd:string))\n"
    "SubClassOf(DataSomeValuesFrom(hasName xsd:string) Named)\n"
    'SubClassOf(Employee DataHasValue(hasId "123"^^xsd:integer))\n'
    "SubClassOf(DataSomeValuesFrom(hasId xsd:integer) Identified)\n"
    "SubClassOf(Doc DataSomeValuesFrom(len DatatypeRestriction(xsd:int "
    'xsd:minInclusive "1"^^xsd:int)))',
]


@pytest.mark.parametrize("i", range(len(CASES)))
def test_native_matches_python(i):
    assert_equivalent(CASES[i])


def test_native_matches_python_synthetic():
    from distel_tpu.frontend.ontology_tools import synthetic_ontology

    text = synthetic_ontology(
        n_classes=150, n_anatomy=40, n_locations=40, n_definitions=20
    )
    assert_equivalent(text)


def test_native_random_ontologies():
    import random
    from tests.test_engine_dense import _random_ontology

    for seed in range(6):
        rng = random.Random(seed * 31 + 7)
        assert_equivalent(_random_ontology(rng))


def test_native_removed_report():
    rep = native_loader.removed_report(
        "SubClassOf(A ObjectUnionOf(B C))\nHasKey(A () (p))\n"
        "ReflexiveObjectProperty(r)"
    )
    assert rep.get("SubClassOf(non-EL)") == 1
    assert rep.get("HasKey") == 1
    assert rep.get("ReflexiveObjectProperty") == 1


def test_native_parse_error():
    with pytest.raises(ValueError, match="native parse error"):
        native_loader.load_indexed("SubClassOf(A <unclosed")


def test_native_removed_in_summary():
    from distel_tpu.runtime.classifier import ELClassifier

    res = ELClassifier().classify_text(
        "SubClassOf(A B)\nSubClassOf(C ObjectUnionOf(D E))"
    )
    assert res.summary()["removed_axioms"] == 1


def test_native_links_role_grouped():
    """The native plane's links arrive role-grouped (role_sort_links
    post-pass) so the engines' tile-sparse matmul sees clustered masks,
    and the CR4/CR6 row arrays are role-sorted for the same reason."""
    from distel_tpu.frontend.ontology_tools import snomed_shaped_ontology

    idx = native_loader.load_indexed(
        snomed_shaped_ontology(n_classes=400, n_roles=24)
    )
    assert idx.n_links > 0
    assert (np.diff(idx.links[:, 0]) >= 0).all()
    if len(idx.nf4) > 1:
        assert (np.diff(idx.nf4[:, 0]) >= 0).all()
    if len(idx.chain_pairs) > 1:
        assert (np.diff(idx.chain_pairs[:, 0]) >= 0).all()


def test_cross_plane_snapshot_resume():
    """A snapshot saved from the Python plane resumes against the native
    plane's numbering: generated (gensym/aux) entities are dropped at
    alignment — their names collide across planes while denoting
    different expressions — and re-derived by the resumed saturation."""
    import os
    import tempfile

    from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine
    from distel_tpu.frontend.ontology_tools import snomed_shaped_ontology
    from distel_tpu.runtime.checkpoint import (
        load_snapshot_state,
        save_snapshot,
    )

    text = snomed_shaped_ontology(n_classes=300, n_roles=16)
    pidx = index_ontology(normalize(parser.parse(text)))
    pres = RowPackedSaturationEngine(pidx).saturate()
    nidx = native_loader.load_indexed(text)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "s.npz")
        save_snapshot(p, pres)
        state, _ = load_snapshot_state(p, idx=nidx)
        resumed = RowPackedSaturationEngine(nidx).saturate(initial=state)
    fresh = RowPackedSaturationEngine(nidx).saturate()
    orig = set(nidx.original_classes.tolist())
    for c in nidx.original_classes.tolist():
        a = {s for s in resumed.subsumers(c) if s in orig}
        b = {s for s in fresh.subsumers(c) if s in orig}
        assert a == b, nidx.concept_names[c]
