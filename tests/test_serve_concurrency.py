"""Genuinely concurrent serve-plane coverage (ISSUE 6 satellite):
threads hammering one ontology while the registry spills/restores/
migrates under a starvation-level memory budget, a registry-level
export race against live writers, and scheduler queue-full behavior
under a concurrent client herd — the paths test_serve.py only walks
single-threaded."""

import threading
import time

import pytest

from distel_tpu.serve.registry import OntologyRegistry, UnknownOntology
from distel_tpu.serve.scheduler import QueueFull, RequestScheduler
from distel_tpu.serve.server import ServeApp, make_server
from distel_tpu.serve.client import ServeClient

BASE = """
SubClassOf(A B)
SubClassOf(B C)
SubClassOf(C ObjectSomeValuesFrom(r D))
SubClassOf(ObjectSomeValuesFrom(r D) E)
"""

ONTO_B = "SubClassOf(P Q)\nSubClassOf(Q S)\n"


def _direct_subsumers(texts, cls):
    from distel_tpu.core.incremental import IncrementalClassifier
    from distel_tpu.runtime.taxonomy import extract_taxonomy

    inc = IncrementalClassifier()
    inc._FAST_PATH_MIN_CONCEPTS = 0
    for t in texts:
        inc.add_text(t)
    return extract_taxonomy(inc.last_result).subsumers[cls]


# ------------------------------------------ spill/restore under traffic


def test_concurrent_clients_through_spill_restore_churn(tmp_path):
    """A 1-byte budget makes EVERY cross-ontology touch evict the other
    ontology: concurrent clients on two ontologies force constant
    spill/restore interleaving.  Nothing may fail, and the final closure
    must equal a direct classifier fed the same delta set (EL+ is
    monotone — application order across threads cannot matter)."""
    app = ServeApp(
        memory_budget_bytes=1,
        spill_dir=str(tmp_path),
        fast_path_min_concepts=0,
        workers=2,
    )
    server = make_server(app)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    client = ServeClient(
        f"http://127.0.0.1:{server.server_address[1]}", timeout=300
    )
    try:
        oid_a = client.load(BASE)["id"]
        oid_b = client.load(ONTO_B)["id"]
        failures = []
        applied = {}  # thread name → delta texts it got acknowledged
        stop = threading.Event()

        def hammer(name, oid, base_cls, delta_parent):
            mine = []
            i = 0
            while not stop.is_set():
                try:
                    if i % 3 == 2:
                        client.subsumers(oid, base_cls)
                    else:
                        text = f"SubClassOf({name}x{i} {delta_parent})"
                        client.delta(oid, text)
                        mine.append(text)
                    i += 1
                except Exception as e:  # noqa: BLE001 — the assertion
                    failures.append((name, e))
            applied[name] = mine

        spec = [
            ("ta0", oid_a, "A", "A"),
            ("ta1", oid_a, "A", "B"),
            ("tb0", oid_b, "P", "P"),
        ]
        threads = [
            threading.Thread(target=hammer, args=s) for s in spec
        ]
        for t in threads:
            t.start()
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=300)
        assert failures == []
        # churn actually happened: at least one eviction+restore cycle
        m = client.metrics_text()

        def metric(name):
            for line in m.splitlines():
                if line.startswith(name + " "):
                    return float(line.rsplit(" ", 1)[1])
            return 0.0

        assert metric("distel_registry_evictions_total") >= 1
        assert metric("distel_registry_restores_total") >= 1
        # the closure absorbed every acknowledged delta, in any order
        texts_a = [BASE] + applied["ta0"] + applied["ta1"]
        got = client.subsumers(oid_a, "A")["subsumers"]
        assert got == _direct_subsumers(texts_a, "A")
        if applied["ta0"]:
            probe = applied["ta0"][0].split()[0].split("(")[1]
            got = client.subsumers(oid_a, probe)["subsumers"]
            assert got == _direct_subsumers(texts_a, probe)
        texts_b = [ONTO_B] + applied["tb0"]
        got = client.subsumers(oid_b, "P")["subsumers"]
        assert got == _direct_subsumers(texts_b, "P")
    finally:
        server.shutdown()
        server.server_close()
        app.close(final_spill=False)


# ---------------------------------------------- export vs live writers


def test_registry_export_serializes_after_inflight_delta(tmp_path):
    """A registry-level export (the migration spill) taken while a
    writer holds the entry must wait the writer out: the handoff record
    carries EXACTLY the acknowledged texts — never a torn state."""
    from distel_tpu.config import ClassifierConfig

    reg = OntologyRegistry(
        ClassifierConfig(), spill_dir=str(tmp_path),
        fast_path_min_concepts=0,
    )
    oid = reg.new_id()
    reg.load(oid, BASE)
    acked = []
    errs = []
    exported = {}
    start = threading.Event()

    def writer():
        start.wait(5)
        for i in range(6):
            text = f"SubClassOf(W{i} A)"
            try:
                reg.delta(oid, [text])
                acked.append(text)
            except UnknownOntology:
                return  # export won the race at an increment boundary
            except Exception as e:  # noqa: BLE001
                errs.append(e)
                return

    def exporter():
        start.wait(5)
        time.sleep(0.05)  # land mid-writer
        exported.update(reg.export(oid))

    tw = threading.Thread(target=writer)
    te = threading.Thread(target=exporter)
    tw.start()
    te.start()
    start.set()
    tw.join(timeout=300)
    te.join(timeout=300)
    assert errs == []
    assert exported, "export never completed"
    # exact consistency: the spilled texts are the base + every
    # acknowledged delta (an unacked delta must not be in the record)
    assert exported["texts"] == [BASE] + acked
    # the handoff restores to a classifier that answers for all of them
    rec = reg.adopt(
        oid, exported["texts"], spill_path=exported["spill"], warm=True
    )
    assert rec["resident"] is True
    from distel_tpu.runtime.taxonomy import extract_taxonomy

    tax = extract_taxonomy(reg.classifier(oid).last_result)
    assert tax.subsumers["A"] == _direct_subsumers(
        exported["texts"], "A"
    )
    # double adopt of a live id is refused loudly
    with pytest.raises(ValueError):
        reg.adopt(oid, exported["texts"], spill_path=exported["spill"])


# ------------------------------- snapshot reads vs a live delta writer


def test_snapshot_reads_consistent_under_delta_writes():
    """The query-plane consistency contract (ISSUE 11): reader threads
    hammer the lock-free /query endpoints while a writer applies deltas
    in a loop.  Every response must be internally consistent — version
    MONOTONIC per client, and the subsumer set byte-identical to a
    closure recomputed from exactly the texts acknowledged at that
    version (never a torn mix of two versions)."""
    app = ServeApp(fast_path_min_concepts=0, workers=1)
    server = make_server(app)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}"
    writer_client = ServeClient(url, timeout=300)
    try:
        rec = writer_client.load(BASE)
        oid = rec["id"]
        # version → the exact text prefix acknowledged at it.  The
        # writer applies deltas ONE at a time (sequential acks, no
        # coalescing possible), so version k ⇔ first k texts.
        texts_at = {rec["version"]: [BASE]}
        stop = threading.Event()
        observations = []  # (version, frozenset(subsumers)) per read
        failures = []

        def reader(k):
            c = ServeClient(url, timeout=300)
            last = 0
            while not stop.is_set():
                try:
                    r = c.query_subsumers(oid, "A")
                    s = c.is_subsumed(oid, "A", "C")
                except Exception as e:  # noqa: BLE001 — the assertion
                    failures.append((k, repr(e)))
                    return
                for v in (r["version"], s["version"]):
                    if v < last:
                        failures.append(
                            (k, f"version went back: {last}->{v}")
                        )
                        return
                    last = max(last, v)
                if not s["subsumed"]:
                    failures.append((k, "A ⊑ C lost"))
                    return
                observations.append(
                    (r["version"], tuple(r["subsumers"]))
                )

        readers = [
            threading.Thread(target=reader, args=(k,)) for k in range(3)
        ]
        for t in readers:
            t.start()
        for i in range(6):
            text = f"SubClassOf(A W{i})\nSubClassOf(W{i} W{i}x)"
            rec = writer_client.delta(oid, text)
            texts_at[rec["version"]] = (
                texts_at[max(texts_at)] + [text]
            )
        stop.set()
        for t in readers:
            t.join(timeout=300)
        assert failures == []
        assert observations
        # every observed (version, subsumers) pair must equal a closure
        # recomputed from exactly that version's acknowledged texts
        seen = {}
        for v, subs in observations:
            prev = seen.setdefault(v, subs)
            assert prev == subs, (
                f"torn read at version {v}: {prev} vs {subs}"
            )
        for v, subs in sorted(seen.items()):
            assert v in texts_at, (v, sorted(texts_at))
            want = tuple(_direct_subsumers(texts_at[v], "A"))
            assert subs == want, (v, subs, want)
        # the readers actually spanned multiple versions
        assert len(seen) >= 2, sorted(seen)
    finally:
        server.shutdown()
        server.server_close()
        app.close(final_spill=False)


# ------------------------------------------- queue-full under a herd


def test_scheduler_queue_full_under_concurrent_herd():
    """16 concurrent submitters against workers=2, max_queue=4: every
    request either completes or is refused with QueueFull at admission —
    no hangs, no lost results, and the queue drains to zero."""
    gate = threading.Event()
    executed = []
    exec_lock = threading.Lock()

    def execute(key, kind, payloads):
        gate.wait(timeout=60)
        with exec_lock:
            executed.extend(payloads)
        return len(payloads)

    sched = RequestScheduler(
        execute, workers=2, max_queue=4, max_batch=1
    )
    admitted, rejected, done, hung = [], [], [], []
    lock = threading.Lock()

    def submitter(i):
        try:
            req = sched.submit(
                f"k{i % 4}", "op", f"p{i}", deadline_s=60
            )
        except QueueFull:
            with lock:
                rejected.append(i)
            return
        with lock:
            admitted.append(i)
        try:
            req.wait(60)
            with lock:
                done.append(i)
        except Exception:  # noqa: BLE001
            with lock:
                hung.append(i)

    threads = [
        threading.Thread(target=submitter, args=(i,)) for i in range(16)
    ]
    for t in threads:
        t.start()
    # let the herd collide with the bounded queue before releasing
    time.sleep(0.3)
    gate.set()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "a submitter hung"
    try:
        assert len(admitted) + len(rejected) == 16
        # the bounded queue really rejected under pressure: 2 workers
        # can hold at most 2 executing + 4 queued when the herd lands
        assert rejected, "herd never hit the bound"
        assert sorted(done) == sorted(admitted)
        assert hung == []
        # every admitted payload executed exactly once
        assert sorted(executed) == sorted(
            f"p{i}" for i in admitted
        )
        deadline = time.monotonic() + 10
        while sched.depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sched.depth() == 0
    finally:
        sched.close()
