from distel_tpu.owl import parser, syntax as S
from distel_tpu.owl.writer import ontology_to_str

PIZZA_MINI = """
Prefix(:=<http://example.org/pizza#>)
Prefix(owl:=<http://www.w3.org/2002/07/owl#>)
Ontology(<http://example.org/pizza>
Declaration(Class(:Pizza))
Declaration(Class(:MeatyPizza))
Declaration(ObjectProperty(:hasTopping))
Declaration(NamedIndividual(:myPizza))
SubClassOf(:MeatyPizza :Pizza)
SubClassOf(:MeatyPizza ObjectSomeValuesFrom(:hasTopping :MeatTopping))
EquivalentClasses(:VegPizza ObjectIntersectionOf(:Pizza :NoMeat))
DisjointClasses(:MeatTopping :VegTopping)
SubObjectPropertyOf(:hasDirectTopping :hasTopping)
SubObjectPropertyOf(ObjectPropertyChain(:hasPart :hasPart) :hasPart)
TransitiveObjectProperty(:hasPart)
ObjectPropertyDomain(:hasTopping :Pizza)
ObjectPropertyRange(:hasTopping :Topping)
ClassAssertion(:Pizza :myPizza)
ObjectPropertyAssertion(:hasTopping :myPizza :t1)
)
"""


def test_parse_pizza_mini():
    onto = parser.parse(PIZZA_MINI)
    assert onto.iri == "http://example.org/pizza"
    kinds = [type(ax).__name__ for ax in onto.axioms]
    assert kinds == [
        "SubClassOf",
        "SubClassOf",
        "EquivalentClasses",
        "DisjointClasses",
        "SubObjectPropertyOf",
        "SubObjectPropertyOf",
        "TransitiveObjectProperty",
        "ObjectPropertyDomain",
        "ObjectPropertyRange",
        "ClassAssertion",
        "ObjectPropertyAssertion",
    ]
    sub = onto.axioms[1]
    assert isinstance(sub.sup, S.ObjectSomeValuesFrom)
    assert sub.sup.role.iri == "http://example.org/pizza#hasTopping"
    chain_ax = onto.axioms[5]
    assert len(chain_ax.chain) == 2
    # declared individual recognized in assertions
    ca = onto.axioms[9]
    assert isinstance(ca.individual, S.Individual)


def test_prefix_expansion_and_thing():
    onto = parser.parse(
        "Prefix(ex:=<http://e/>)\n"
        "Ontology(\nSubClassOf(ex:A owl:Thing)\nSubClassOf(owl:Nothing ex:B)\n)"
    )
    a1, a2 = onto.axioms
    assert a1.sub == S.Class("http://e/A")
    assert a1.sup is S.OWL_THING
    assert a2.sub is S.OWL_NOTHING


def test_bare_axiom_stream():
    onto = parser.parse("SubClassOf(A B)\nSubClassOf(B C)")
    assert len(onto) == 2
    assert onto.axioms[0].sub == S.Class("A")


def test_unsupported_constructs_survive():
    onto = parser.parse(
        "Ontology(\n"
        "SubClassOf(A ObjectUnionOf(B C))\n"
        "HasKey(A () (p))\n"
        "SubClassOf(ObjectComplementOf(A) B)\n"
        ")"
    )
    assert isinstance(onto.axioms[0].sup, S.UnsupportedClassExpression)
    assert isinstance(onto.axioms[1], S.UnsupportedAxiom)
    assert isinstance(onto.axioms[2].sub, S.UnsupportedClassExpression)


def test_annotations_and_declarations_skipped():
    onto = parser.parse(
        "Ontology(\n"
        "Declaration(Class(A))\n"
        'AnnotationAssertion(rdfs:label A "a label")\n'
        "SubClassOf(Annotation(rdfs:comment \"c\") A B)\n"
        ")"
    )
    assert len(onto) == 1
    assert isinstance(onto.axioms[0], S.SubClassOf)


def test_roundtrip_through_writer():
    onto = parser.parse(PIZZA_MINI)
    text = ontology_to_str(onto)
    onto2 = parser.parse(text)
    assert len(onto2) == len(onto)
    assert [type(a) for a in onto2.axioms] == [type(a) for a in onto.axioms]


def test_entity_collection():
    onto = parser.parse(PIZZA_MINI)
    classes = {c.iri.split("#")[-1] for c in onto.classes()}
    assert {"Pizza", "MeatyPizza", "MeatTopping", "VegPizza"} <= classes
    roles = {r.iri.split("#")[-1] for r in onto.roles()}
    assert {"hasTopping", "hasPart", "hasDirectTopping"} <= roles
    inds = {i.iri.split("#")[-1] for i in onto.individuals()}
    assert {"myPizza", "t1"} <= inds


def test_nested_intersections():
    onto = parser.parse(
        "SubClassOf(A ObjectIntersectionOf(B ObjectSomeValuesFrom(r "
        "ObjectIntersectionOf(C D)) E))"
    )
    sup = onto.axioms[0].sup
    assert isinstance(sup, S.ObjectIntersectionOf)
    assert len(sup.operands) == 3
    some = sup.operands[1]
    assert isinstance(some.filler, S.ObjectIntersectionOf)


def test_object_has_value_desugars_to_nominal_existential():
    # ObjectHasValue(r a) ≡ ∃r.{a} — the reference loads it as a T3₁
    # axiom keyed on the individual (init/AxiomLoader.java:702-711)
    from distel_tpu.core.indexing import index_ontology
    from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine
    from distel_tpu.frontend.normalizer import normalize

    text = (
        "SubClassOf(Cat ObjectHasValue(owns felix))\n"
        "SubClassOf(ObjectHasValue(owns felix) FelixOwner)\n"
        "SubClassOf(ObjectSomeValuesFrom(owns ObjectOneOf(felix)) FelixOwner2)\n"
    )
    idx = index_ontology(normalize(parser.parse(text)))
    r = RowPackedSaturationEngine(idx).saturate()
    subs = {
        idx.concept_names[i] for i in r.subsumers(idx.concept_ids["Cat"])
    }
    assert {"FelixOwner", "FelixOwner2"} <= subs
