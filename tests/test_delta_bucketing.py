"""Bucketed delta programs (ISSUE 10): the incremental fast path's B
(delta rows) and cross (full tables × new-link window) programs in
shape-bucketed mode — byte-identical closures vs the exact-shape path
and vs a cold batch run, program-registry reuse across deltas AND
across ontologies, the exact-shape fallback at the padding-reservation
edge, the env hatch, the promoted config knob, and the warmup plane's
delta-roster coverage.

The soundness claim under test: a bucketed delta program pins the base
engine's state layout verbatim (the programs round-robin over ONE
packed state) while its own table rows, gate/selection arrays and the
link-window bounds ride as runtime arguments over ladder-quantized
capacities — so the traced program is a pure function of the delta
bucket signature, and steady-state delta traffic compiles once per
bucket per process, ever."""

import pytest

from distel_tpu.config import ClassifierConfig
from distel_tpu.core.incremental import IncrementalClassifier
from distel_tpu.core.indexing import index_ontology
from distel_tpu.core.program_cache import PROGRAMS
from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine
from distel_tpu.frontend.normalizer import normalize
from distel_tpu.owl import parser


def _mk_base(p=""):
    """Small base exercising every rule family a delta can extend:
    subclass chains (CR1), an existential + axiom pair (CR3/CR4), a
    role chain (CR6), and a second role ``s`` so an ``r ⊑ s`` delta
    rebinds between EXISTING roles."""
    return (
        f"SubClassOf({p}A {p}B)\nSubClassOf({p}B {p}C)\n"
        f"SubClassOf({p}C ObjectSomeValuesFrom(r {p}D))\n"
        f"SubClassOf(ObjectSomeValuesFrom(r {p}D) {p}E)\n"
        f"SubClassOf({p}E {p}F)\n"
        f"SubObjectPropertyOf(ObjectPropertyChain(r r) r)\n"
        f"SubClassOf({p}G ObjectSomeValuesFrom(s {p}H))\n"
        f"SubClassOf(ObjectSomeValuesFrom(s {p}H) {p}I)\n"
    )


_DELTAS = {
    "class-only": (
        "SubClassOf(New0 A)\n"
        "SubClassOf(ObjectIntersectionOf(F C) NewBoth)\n"
    ),
    "link-creating": "SubClassOf(NewL ObjectSomeValuesFrom(r B))\n",
    "role-adding": (
        "SubObjectPropertyOf(tNew r)\n"
        "SubClassOf(NewR ObjectSomeValuesFrom(tNew D))\n"
    ),
    "rebind": (
        "SubObjectPropertyOf(r s)\n"
        "SubClassOf(NewQ ObjectSomeValuesFrom(r H))\n"
    ),
}


def _sub_map(res, idx):
    """Full name-keyed subsumer map — the comparison idiom of
    test_runtime's fast-path suite (incremental and batch numberings
    differ; names are the common key)."""
    return {
        idx.concept_names[x]: {
            idx.concept_names[i]
            for i in res.subsumers(x)
            if i < idx.n_concepts
        }
        for x in range(idx.n_concepts)
    }


def _inc_sub_map(inc, batch_idx):
    r = inc.last_result
    return {
        batch_idx.concept_names[x]: {
            r.idx.concept_names[i]
            for i in r.subsumers(
                r.idx.concept_ids[batch_idx.concept_names[x]]
            )
            if i < r.idx.n_concepts
        }
        for x in range(batch_idx.n_concepts)
    }


def _fast_inc(**cfg_kw):
    cfg = ClassifierConfig(fast_path_min_concepts=0, **cfg_kw)
    return IncrementalClassifier(cfg)


# ------------------------------------------------------ closure parity


@pytest.mark.parametrize("kind", sorted(_DELTAS))
def test_bucketed_delta_matches_batch(kind):
    base, delta = _mk_base(), _DELTAS[kind]
    inc = _fast_inc()
    inc.add_text(base)
    base_engine = inc._base_engine
    inc.add_text(delta)
    rec = inc.history[-1]
    assert rec["path"] == "fast", rec
    assert inc._base_engine is base_engine  # no rebuild
    assert rec["delta_bucketed"] is True, rec
    batch_idx = index_ontology(normalize(parser.parse(base + delta)))
    batch = RowPackedSaturationEngine(batch_idx).saturate()
    assert _inc_sub_map(inc, batch_idx) == _sub_map(batch, batch_idx)


def test_bucketed_vs_exact_delta_same_closure(monkeypatch):
    """The A/B the bench leans on: the env hatch's exact-shape delta
    programs and the bucketed ones produce identical subsumer maps."""
    base = _mk_base()
    delta = _DELTAS["link-creating"] + _DELTAS["class-only"]
    batch_idx = index_ontology(normalize(parser.parse(base + delta)))
    maps = {}
    for hatch in (True, False):
        inc = _fast_inc()
        inc.add_text(base)
        if hatch:
            monkeypatch.setenv("DISTEL_EXACT_DELTA_PROGRAMS", "1")
        else:
            monkeypatch.delenv(
                "DISTEL_EXACT_DELTA_PROGRAMS", raising=False
            )
        inc.add_text(delta)
        rec = inc.history[-1]
        assert rec["path"] == "fast", rec
        assert rec["delta_bucketed"] is (not hatch), rec
        maps[hatch] = _inc_sub_map(inc, batch_idx)
    assert maps[True] == maps[False]


# --------------------------------------------------- program reuse


def test_second_same_bucket_delta_hits_registry():
    """Steady state on ONE ontology: the second same-shape delta builds
    zero programs — registry hit, ~0 compile."""
    inc = _fast_inc()
    inc.add_text(_mk_base())
    inc.add_text("SubClassOf(Steady0 A)\n")
    first = inc.history[-1]
    assert first["delta_programs"] > 0, first
    inc.add_text("SubClassOf(Steady1 A)\n")
    rec = inc.history[-1]
    assert rec["path"] == "fast", rec
    assert rec["program_cache_hit"] is True, rec
    assert rec["delta_program_hits"] == rec["delta_programs"] > 0, rec
    assert rec["compile_s"] == 0.0 and rec["trace_lower_s"] == 0.0, rec


def test_same_bucket_delta_shared_across_ontologies():
    """The fleet-wide claim: a DIFFERENT ontology in the same bucket
    reuses the delta programs compiled for the first one — same shapes,
    different names/wiring, zero compile."""
    inc_a = _fast_inc()
    inc_a.add_text(_mk_base("P"))
    inc_a.add_text("SubClassOf(PNew PA)\n")
    sig_a = inc_a.history[-1]["delta_signature"]
    assert sig_a
    inc_b = _fast_inc()
    inc_b.add_text(_mk_base("Q"))
    inc_b.add_text("SubClassOf(QNew QA)\n")
    rec = inc_b.history[-1]
    assert rec["delta_signature"] == sig_a
    assert rec["program_cache_hit"] is True, rec
    assert rec["compile_s"] == 0.0, rec
    # ...and the shared program computed THIS ontology's closure
    full = _mk_base("Q") + "SubClassOf(QNew QA)\n"
    batch_idx = index_ontology(normalize(parser.parse(full)))
    batch = RowPackedSaturationEngine(batch_idx).saturate()
    assert _inc_sub_map(inc_b, batch_idx) == _sub_map(batch, batch_idx)


def test_link_capacity_edge_falls_back_exact():
    """A delta growing the link table exactly to the base's padded
    capacity leaves no dead link row for the quantized plans' pad
    segments: the fast path must fall back to exact-shape programs
    (still fast-path, still byte-identical) instead of bucketing."""
    base = _mk_base()
    inc = _fast_inc()
    inc._LINK_PAD = 0  # base.nl lands on the 32 floor rung
    inc.add_text(base)
    base_engine = inc._base_engine
    nl, n0 = base_engine.nl, inc._base_idx.n_links
    assert nl == 32, nl  # premise: floor rung
    delta = "".join(
        f"SubClassOf(Fill{k} ObjectSomeValuesFrom(r Mk{k}))\n"
        for k in range(nl - n0)
    )
    inc.add_text(delta)
    rec = inc.history[-1]
    assert inc.last_result.idx.n_links == nl  # premise: exactly full
    assert rec["path"] == "fast", rec
    assert inc._base_engine is base_engine
    assert rec["delta_bucketed"] is False, rec
    batch_idx = index_ontology(normalize(parser.parse(base + delta)))
    batch = RowPackedSaturationEngine(batch_idx).saturate()
    assert _inc_sub_map(inc, batch_idx) == _sub_map(batch, batch_idx)


# -------------------------------------------------- knob + warmup


def test_fast_path_threshold_is_a_config_knob(tmp_path):
    assert ClassifierConfig().fast_path_min_concepts == 2_048
    p = tmp_path / "t.properties"
    p.write_text("fast.path.min.concepts = 7\n")
    cfg = ClassifierConfig.from_properties(str(p))
    assert cfg.fast_path_min_concepts == 7
    inc = IncrementalClassifier(cfg)
    assert inc._FAST_PATH_MIN_CONCEPTS == 7
    # the config default drives path selection: a tiny corpus under
    # the threshold rebuilds, with the knob at 0 it fast-paths
    inc = IncrementalClassifier(ClassifierConfig())
    inc.add_text("SubClassOf(A B)\n")
    inc.add_text("SubClassOf(C A)\n")
    assert inc.history[-1]["path"] == "rebuild"
    inc = _fast_inc()
    inc.add_text("SubClassOf(A B)\n")
    inc.add_text("SubClassOf(C A)\n")
    assert inc.history[-1]["path"] == "fast"


def test_warmup_covers_first_delta_after_restart():
    """The fleet-restart acceptance: after ``warmup_text`` (serve
    profile) on a sample corpus, a fresh classifier's FIRST class-only
    and link-creating deltas both run compile-free — the warmup AOTs
    the canonical delta rosters, not just the base program."""
    from distel_tpu.runtime import warmup

    cfg = ClassifierConfig(fast_path_min_concepts=0)
    PROGRAMS.clear()
    rec = warmup.warmup_text(_mk_base("W"), cfg, profile="serve")
    assert rec["delta_programs"] >= 3, rec
    inc = IncrementalClassifier(cfg)
    inc.add_text(_mk_base("W"))
    assert inc.history[-1]["program_cache_hit"] is True
    inc.add_text("SubClassOf(WNew WA)\n")
    h = inc.history[-1]
    assert h["program_cache_hit"] is True and h["compile_s"] == 0.0, h
    inc.add_text("SubClassOf(WL ObjectSomeValuesFrom(r WB))\n")
    h = inc.history[-1]
    assert h["program_cache_hit"] is True and h["compile_s"] == 0.0, h
    assert h["delta_program_hits"] == h["delta_programs"] == 2, h
