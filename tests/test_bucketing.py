"""Shape-bucketed programs (ISSUE 2): closure parity with exact-shape
engines on tier-1 corpora, cross-ontology program reuse (in-process
registry AND persistent disk cache), warmup precompile, and the
quantized SegmentedRowOr canonicalization itself.

The soundness claim under test: a bucketed engine's compiled program
depends ONLY on its bucket signature — all ontology content rides in
runtime arguments — so an executable compiled for one ontology is
exactly the right program for any other ontology in the same bucket,
and quantization padding (dead rows, pad segments, inert window slots)
is closure-invisible."""

import numpy as np
import pytest

from distel_tpu.core.indexing import index_ontology
from distel_tpu.core.program_cache import PROGRAMS, bucket_dim
from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine
from distel_tpu.frontend.normalizer import normalize
from distel_tpu.frontend.ontology_tools import (
    snomed_shaped_ontology,
    synthetic_ontology,
)
from distel_tpu.owl import parser
from distel_tpu.testing.differential import diff_engine_vs_oracle

from test_packed_engine import BOTTOM_ONTO


def _indexed(text):
    norm = normalize(parser.parse(text))
    return norm, index_ontology(norm)


def _same_bucket_pair(shift_a=1, shift_b=3, n=240):
    """Two ontologies with IDENTICAL table sizes and segment histograms
    (so they land in one bucket by construction) but different axiom
    WIRING — different gather indices, targets, closures.  A shared
    compiled program is only sound if every ontology-derived array
    really is a runtime argument; this pair is the regression tripwire."""

    def onto(shift):
        lines = []
        for i in range(n):
            lines.append(f"SubClassOf(C{i} C{(i + shift) % n})")
        for i in range(0, n, 4):
            lines.append(
                f"SubClassOf(C{i} ObjectSomeValuesFrom(r D{i % 16}))"
            )
            lines.append(
                f"SubClassOf(ObjectSomeValuesFrom(r D{(i + shift) % 16})"
                f" E{i % 8})"
            )
        return "\n".join(lines)

    return onto(shift_a), onto(shift_b)


def _assert_parity(idx, bucketed_res, exact_res):
    assert bucketed_res.derivations == exact_res.derivations
    s_a = np.asarray(bucketed_res.packed_s)
    s_b = np.asarray(exact_res.packed_s)
    nw = min(s_a.shape[1], s_b.shape[1])
    assert np.array_equal(
        s_a[: idx.n_concepts, :nw], s_b[: idx.n_concepts, :nw]
    )
    r_a = np.asarray(bucketed_res.packed_r)
    r_b = np.asarray(exact_res.packed_r)
    assert np.array_equal(
        r_a[: idx.n_links, :nw], r_b[: idx.n_links, :nw]
    )


# ------------------------------------------------ closure parity


@pytest.mark.parametrize(
    "text,diff_oracle",
    [
        # breadth parity (all rules, every golden fixture) lives in
        # test_golden's rowpacked-bucketed runner; here: the ⊥-heavy
        # fixture with an oracle diff, and the many-role SNOMED shape
        # (the scan-regime corpus) against its exact engine
        (BOTTOM_ONTO, True),
        (snomed_shaped_ontology(n_classes=600), False),
    ],
    ids=["bottom", "snomed-shaped"],
)
def test_bucketed_closure_matches_exact(text, diff_oracle):
    norm, idx = _indexed(text)
    exact = RowPackedSaturationEngine(idx).saturate()
    eng = RowPackedSaturationEngine(idx, bucket=True)
    res = eng.saturate()
    _assert_parity(idx, res, exact)
    if diff_oracle:
        report = diff_engine_vs_oracle(norm, res)
        assert report.ok(), report.summary()


def test_bucketed_resume_from_snapshot_state():
    # embed path: a previous closure re-embeds into the bucketed layout
    # and the resumed fixed point derives NOTHING new (it was converged)
    norm, idx = _indexed(BOTTOM_ONTO)
    first = RowPackedSaturationEngine(idx, bucket=True).saturate()
    eng = RowPackedSaturationEngine(idx, bucket=True)
    resumed = eng.saturate(
        initial=(first.packed_s, first.packed_r)
    )
    assert resumed.derivations == 0
    s_a, s_b = np.asarray(resumed.packed_s), np.asarray(first.packed_s)
    nw = min(s_a.shape[1], s_b.shape[1])
    assert np.array_equal(
        s_a[: idx.n_concepts, :nw], s_b[: idx.n_concepts, :nw]
    )


# -------------------------------------- cross-ontology program reuse


def test_same_bucket_different_ontology_shares_program():
    text_a, text_b = _same_bucket_pair()
    _, idx_a = _indexed(text_a)
    _, idx_b = _indexed(text_b)
    eng_a = RowPackedSaturationEngine(idx_a, bucket=True)
    eng_b = RowPackedSaturationEngine(idx_b, bucket=True)
    assert eng_a.bucket_signature == eng_b.bucket_signature
    res_a = eng_a.saturate()
    assert not eng_a.compile_stats.program_cache_hit or (
        PROGRAMS.stats()["programs"] > 0
    )
    cold = eng_a.compile_stats.compile_s + eng_a.compile_stats.trace_lower_s
    res_b = eng_b.saturate()
    # the acceptance demo: the second, DIFFERENT ontology skips
    # compilation outright (program-registry hit), ≥10x under the cold
    # program build
    assert eng_b.compile_stats.program_cache_hit
    warm = eng_b.compile_stats.compile_s + eng_b.compile_stats.trace_lower_s
    assert cold > 0.0 and warm * 10 <= cold
    # ...and the SHARED program computes each ontology's own closure
    for idx, res in ((idx_a, res_a), (idx_b, res_b)):
        exact = RowPackedSaturationEngine(idx).saturate()
        _assert_parity(idx, res, exact)


def test_persistent_cache_hit_across_program_registry_clear(tmp_path):
    """Disk-cache half of the story: byte-identical HLO ⇒ the XLA
    compile of a fresh (registry-cold) engine deserializes from the
    persistent cache — the cross-PROCESS warm path, exercised in one
    process by clearing every in-memory cache."""
    import jax

    from distel_tpu.runtime.instrumentation import PERSISTENT_CACHE_EVENTS

    text_a, text_b = _same_bucket_pair()
    _, idx_a = _indexed(text_a)
    _, idx_b = _indexed(text_b)
    from jax._src import compilation_cache

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # the cache singleton latches its directory on first use — reset so
    # the tmp_path actually takes effect mid-process
    compilation_cache.reset_cache()
    try:
        # an earlier test may have registered this bucket's program
        # in-process — drop it so precompile really writes to disk.
        # (AOT ``lowered.compile()`` never consults jax's jit dispatch
        # cache, so clearing the registry alone makes the disk the only
        # warm layer — no suite-slowing ``jax.clear_caches()`` needed.)
        PROGRAMS.clear()
        eng_a = RowPackedSaturationEngine(idx_a, bucket=True)
        eng_a.precompile(programs=("run",))
        PROGRAMS.clear()
        eng_b = RowPackedSaturationEngine(idx_b, bucket=True)
        assert eng_b.bucket_signature == eng_a.bucket_signature
        res_b = eng_b.saturate()
        st = eng_b.compile_stats
        assert not st.program_cache_hit
        assert st.persistent_cache_hits > 0
        exact = RowPackedSaturationEngine(idx_b).saturate()
        _assert_parity(idx_b, res_b, exact)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min
        )
        compilation_cache.reset_cache()


# (warmup → serve-bucket reuse is covered end-to-end by test_serve.py::
# test_warmup_precompile_makes_same_bucket_load_compile_free, which
# drives runtime/warmup.py through the ServeApp background thread)


# ------------------------------------------------- plan canonicalization


def test_quantized_segor_matches_plain_reduce():
    from distel_tpu.ops.bitpack import SegmentedRowOr

    rng = np.random.default_rng(3)
    qn = lambda n: bucket_dim(n, 2.0, floor=8)  # noqa: E731
    for trial in range(20):
        n_state = int(rng.integers(4, 30))
        k = int(rng.integers(1, 80))
        targets = rng.integers(0, n_state - 1, size=k)
        plan = SegmentedRowOr.quantized(targets, qn, n_state - 1, k)
        rows = rng.integers(0, 2**32, size=(k, 3), dtype=np.uint32)
        state = rng.integers(0, 2**32, size=(n_state, 3), dtype=np.uint32)
        # engine convention: pad slot k gathers the dead row itself —
        # a self-loop, the identity under OR
        srcs = np.vstack([rows, state[n_state - 1 : n_state]])
        got = np.asarray(plan.write(state, plan.reduce(srcs[plan.order])))
        want = state.copy()
        for t, row in zip(targets, rows):
            want[t] |= row
        assert (got == want).all(), trial


def test_quantized_segor_structure_collides_across_wirings():
    from distel_tpu.ops.bitpack import SegmentedRowOr

    qn = lambda n: bucket_dim(n, 2.0, floor=8)  # noqa: E731
    a = np.repeat(np.arange(40), 2)  # every target twice
    b = np.repeat(np.arange(100, 140)[::-1], 2)  # different rows, same shape
    pa = SegmentedRowOr.quantized(a, qn, 999, len(a))
    pb = SegmentedRowOr.quantized(b, qn, 999, len(b))
    assert pa.structure() == pb.structure()


def test_bucket_dim_ladder_is_monotone_and_fixed():
    prev = 0
    for n in range(0, 5000, 7):
        v = bucket_dim(n)
        assert v >= n
        assert v >= prev or n == 0
        prev = max(prev, v)
    assert bucket_dim(0) == 0
    assert bucket_dim(1) == 32
    assert bucket_dim(33, floor=1) < bucket_dim(33)  # finer floor family


def test_bucketed_rebind_role_closure_matches_fresh():
    """The masks-only partial rebuild must survive bucketing: the grown
    closure reaches the SHARED compiled program purely through the
    argument pytree (rebuilt mask slabs + window tables), so the rebind
    swaps argument content without perturbing the bucket signature."""
    from test_rowpacked_engine import _REBIND_BASE

    _, idx_old = _indexed(_REBIND_BASE)
    _, idx_new = _indexed(_REBIND_BASE + "SubObjectPropertyOf(r s)\n")
    kw = dict(bucket=True, window_headroom=2)
    fresh = RowPackedSaturationEngine(idx_new, **kw).saturate()
    eng = RowPackedSaturationEngine(idx_old, **kw)
    before = eng.saturate()
    sig0 = eng.bucket_signature
    assert eng.rebind_role_closure(idx_new.role_closure)
    assert eng.bucket_signature == sig0
    resumed = eng.saturate(initial=(before.packed_s, before.packed_r))
    assert np.array_equal(
        np.asarray(resumed.packed_s), np.asarray(fresh.packed_s)
    )
    assert np.array_equal(
        np.asarray(resumed.packed_r), np.asarray(fresh.packed_r)
    )
