"""Serve-plane tests: end-to-end load→query→delta→query over a live
server thread (closure answers must match a direct IncrementalClassifier
run), queue-full 429, deadline 503, eviction-then-reload-from-spill, the
scheduler's batching/serialization contract, and graceful SIGTERM
shutdown with a final snapshot spill."""

import contextlib
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from distel_tpu.core.incremental import IncrementalClassifier
from distel_tpu.serve.client import ServeClient, ServeError
from distel_tpu.serve.metrics import Metrics
from distel_tpu.serve.scheduler import Deadline, QueueFull, RequestScheduler
from distel_tpu.serve.server import ServeApp, make_server

BASE = """
SubClassOf(A B)
SubClassOf(B C)
SubClassOf(C ObjectSomeValuesFrom(r D))
SubClassOf(ObjectSomeValuesFrom(r D) E)
SubClassOf(E F)
"""

# link-creating delta (new filler G ⇒ new link row) over an EXISTING
# role: the reference's property-assertion traffic shape — must ride the
# fast path's cross program, no rebuild
DELTA = """
SubClassOf(New0 A)
SubClassOf(New0 ObjectSomeValuesFrom(r G))
SubClassOf(G D)
"""

#: same SHAPE as DELTA (one nf1 + one link-creating existential + one
#: nf1) — lands in the same delta bucket, so the second increment must
#: be a program-registry hit (ISSUE 10)
DELTA2 = """
SubClassOf(New1 A)
SubClassOf(New1 ObjectSomeValuesFrom(r H))
SubClassOf(H D)
"""


@contextlib.contextmanager
def serving(**kw):
    app = ServeApp(**kw)
    server = make_server(app)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    port = server.server_address[1]
    client = ServeClient(f"http://127.0.0.1:{port}", timeout=300)
    try:
        yield app, client
    finally:
        server.shutdown()
        server.server_close()
        app.close(final_spill=False)
        thread.join(timeout=10)


def _direct_subsumers(texts, cls, fast_min=0):
    """The same texts through a plain IncrementalClassifier — the oracle
    for what the server must answer (the server serves subsumers off the
    taxonomy projection: named signature classes only)."""
    from distel_tpu.runtime.taxonomy import extract_taxonomy

    inc = IncrementalClassifier()
    inc._FAST_PATH_MIN_CONCEPTS = fast_min
    for t in texts:
        inc.add_text(t)
    return extract_taxonomy(inc.last_result).subsumers[cls]


def _metric(text, name):
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(" ", 1)[1])
    return None


# ------------------------------------------------------------ end to end


def test_serve_end_to_end_fast_path(tmp_path):
    with serving(
        fast_path_min_concepts=0, spill_dir=str(tmp_path)
    ) as (app, client):
        rec = client.load(BASE)
        oid = rec["id"]
        assert rec["path"] == "rebuild" and rec["concepts"] > 0

        got = client.subsumers(oid, "A")
        assert got["subsumers"] == _direct_subsumers([BASE], "A")

        d = client.delta(oid, DELTA)
        assert d["path"] == "fast"  # base program reused, no recompile
        assert d["batched"] == 1

        got = client.subsumers(oid, "New0")
        want = _direct_subsumers([BASE, DELTA], "New0")
        assert got["subsumers"] == want
        assert {"A", "B", "C", "E", "F"} <= set(got["subsumers"])

        tax = client.taxonomy(oid)
        assert tax["parents"]["A"] == ["B"]

        health = client.healthz()
        assert health["status"] == "ok"
        assert health["ontologies"] == 1 and health["resident"] == 1

        m = client.metrics_text()
        # the delta rode the fast path: fast-path counter incremented,
        # rebuild counter stayed at the initial load's compile
        assert _metric(m, "distel_deltas_fast_path_total") == 1
        assert _metric(m, "distel_saturation_rebuilds_total") == 1
        assert "distel_requests_total" in m
        assert "distel_request_seconds_bucket" in m
        assert "distel_request_phase_seconds_count" in m
        # the delta-program plane (ISSUE 10): build seconds observed
        # per fast-path increment, and a SECOND same-shape delta is
        # all registry hits — compile-free steady state, visible both
        # in the response record and on /metrics
        assert _metric(m, "distel_delta_compile_seconds_count") == 1
        d2 = client.delta(oid, DELTA2)
        assert d2["path"] == "fast"
        assert d2["program_cache_hit"] is True, d2
        assert d2["compile_s"] == 0.0, d2
        m = client.metrics_text()
        assert _metric(
            m, "distel_delta_program_cache_hits_total"
        ) >= d2["delta_programs"] > 0

        # a second query compiles nothing: rebuild counter unchanged
        client.subsumers(oid, "New0")
        m2 = client.metrics_text()
        assert _metric(m2, "distel_saturation_rebuilds_total") == 1

        # unknown ontology / unknown class are clean 404s
        with pytest.raises(ServeError) as ei:
            client.subsumers("ont-9999", "A")
        assert ei.value.status == 404
        with pytest.raises(ServeError) as ei:
            client.subsumers(oid, "NoSuchClass")
        assert ei.value.status == 404


# -------------------------------------------------- backpressure / 429


def test_queue_full_yields_429(tmp_path):
    with serving(
        workers=1, max_queue=1, spill_dir=str(tmp_path)
    ) as (app, client):
        oid = client.load(BASE)["id"]

        started = threading.Event()
        release = threading.Event()
        real_delta = app.registry.delta

        def slow_delta(o, texts):
            started.set()
            release.wait(timeout=60)
            return real_delta(o, texts)

        app.registry.delta = slow_delta
        results = {}

        def post(name, **kw):
            try:
                results[name] = client.delta(oid, "SubClassOf(X%s A)" % name)
            except ServeError as e:
                results[name] = e

        t1 = threading.Thread(target=post, args=("1",))
        t1.start()
        assert started.wait(timeout=60)  # d1 occupies the only worker
        t2 = threading.Thread(target=post, args=("2",))
        t2.start()
        deadline = time.monotonic() + 60
        while app.scheduler.depth() < 1:  # d2 queued (queue now full)
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # admission control: the bounded queue rejects rather than hangs
        with pytest.raises(ServeError) as ei:
            client.delta(oid, "SubClassOf(X3 A)")
        assert ei.value.status == 429
        assert ei.value.headers.get("Retry-After") == "1"

        release.set()
        t1.join(timeout=120)
        t2.join(timeout=120)
        assert results["1"]["id"] == oid
        assert results["2"]["id"] == oid
        m = client.metrics_text()
        assert _metric(m, "distel_admission_rejected_total") >= 1


# -------------------------------------------------------- deadlines/503


def test_deadline_yields_503_and_worker_recovers(tmp_path):
    with serving(
        workers=1, max_queue=8, spill_dir=str(tmp_path)
    ) as (app, client):
        oid = client.load(BASE)["id"]

        started = threading.Event()
        release = threading.Event()
        real_delta = app.registry.delta

        def slow_delta(o, texts):
            started.set()
            release.wait(timeout=60)
            return real_delta(o, texts)

        app.registry.delta = slow_delta
        # the only worker grinds on a long saturation; an over-deadline
        # request answers 503 instead of wedging the caller
        t1 = threading.Thread(
            target=lambda: client.delta(oid, "SubClassOf(Y1 A)")
        )
        t1.start()
        assert started.wait(timeout=60)  # Y1 occupies the only worker
        with pytest.raises(ServeError) as ei:
            client.delta(oid, "SubClassOf(Y2 A)", deadline_s=0.2)
        assert ei.value.status == 503
        release.set()
        t1.join(timeout=120)

        # worker recovered: a normal request succeeds afterwards
        app.registry.delta = real_delta
        rec = client.delta(oid, "SubClassOf(Y3 A)")
        assert rec["id"] == oid
        m = client.metrics_text()
        assert _metric(m, "distel_deadline_expired_total") >= 1


# -------------------------------------------- eviction / reload from spill


def test_eviction_spills_and_reloads(tmp_path):
    onto_b = "SubClassOf(P Q)\nSubClassOf(Q S)\n"
    with serving(
        memory_budget_bytes=1, spill_dir=str(tmp_path)
    ) as (app, client):
        oid_a = client.load(BASE)["id"]
        oid_b = client.load(onto_b)["id"]
        # loading B pushed A (LRU) over the 1-byte budget → spilled
        spill = tmp_path / f"{oid_a}.snapshot.npz"
        deadline = time.monotonic() + 60
        while not spill.exists():
            assert time.monotonic() < deadline
            time.sleep(0.05)
        health = client.healthz()
        assert health["spilled"] >= 1

        # touching A restores it from the spill — same answers
        got = client.subsumers(oid_a, "A")
        assert got["subsumers"] == _direct_subsumers([BASE], "A")
        m = client.metrics_text()
        assert _metric(m, "distel_registry_evictions_total") >= 1
        assert _metric(m, "distel_registry_restores_total") >= 1

        # B still answers (restored or resident, transparently)
        got_b = client.subsumers(oid_b, "P")
        assert got_b["subsumers"] == _direct_subsumers([onto_b], "P")

        # a delta lands on the restored classifier and stays consistent
        d = client.delta(oid_a, DELTA)
        assert d["id"] == oid_a
        got = client.subsumers(oid_a, "New0")
        assert got["subsumers"] == _direct_subsumers([BASE, DELTA], "New0")


# ----------------------------------------------------- scheduler contract


def test_scheduler_batches_and_serializes_per_key():
    calls = []
    release = threading.Event()

    def execute(key, kind, payloads):
        if kind == "block":
            release.wait(timeout=60)
        calls.append((key, kind, list(payloads)))
        return {"key": key, "n": len(payloads)}

    m = Metrics()
    sched = RequestScheduler(
        execute, workers=1, max_queue=16, max_batch=4, metrics=m
    )
    try:
        blocker = sched.submit("A", "block", None, deadline_s=60)
        # queued behind the blocker on another lane: contiguous
        # batchable deltas coalesce into ONE executor call
        reqs = [
            sched.submit("B", "delta", f"d{i}", deadline_s=60,
                         batchable=True)
            for i in range(3)
        ]
        tail = sched.submit("B", "query", "q", deadline_s=60)
        release.set()
        assert blocker.wait(60)["key"] == "A"
        for r in reqs:
            out = r.wait(60)
            assert out == {"key": "B", "n": 3}
            assert r.batched == 3
        assert tail.wait(60)["n"] == 1
        kinds = [(k, kind, p) for k, kind, p in calls]
        assert ("B", "delta", ["d0", "d1", "d2"]) in kinds
        # the non-batchable query ran AFTER the batch (per-key FIFO)
        assert kinds.index(("B", "query", ["q"])) > kinds.index(
            ("B", "delta", ["d0", "d1", "d2"])
        )
        # queue-full admission is an exception, not a hang
        ev = threading.Event()

        def execute_never(*a):
            ev.wait(60)

        sched2 = RequestScheduler(execute_never, workers=1, max_queue=1)
        try:
            sched2.submit("X", "block", None, deadline_s=60)
            deadline = time.monotonic() + 60
            while sched2.depth() > 0:  # wait for the worker to pick it
                assert time.monotonic() < deadline
                time.sleep(0.01)
            sched2.submit("X", "q", None, deadline_s=60)  # fills queue
            with pytest.raises(QueueFull):
                sched2.submit("X", "q", None, deadline_s=60)
        finally:
            ev.set()
            sched2.close()
        # queued-past-deadline requests fail fast without executing
        ev3 = threading.Event()

        def execute_slow(key, kind, payloads):
            ev3.wait(timeout=5)
            return "done"

        sched3 = RequestScheduler(execute_slow, workers=1, max_queue=8)
        try:
            first = sched3.submit("K", "x", None, deadline_s=60)
            doomed = sched3.submit("K", "x", None, deadline_s=0.01)
            time.sleep(0.05)
            ev3.set()
            assert first.wait(60) == "done"
            with pytest.raises(Deadline):
                doomed.wait(60)
        finally:
            sched3.close()
    finally:
        sched.close()


def test_metrics_render_format():
    m = Metrics()
    m.describe("foo_total", "a counter")
    m.counter_inc("foo_total", {"kind": "x"})
    m.counter_inc("foo_total", {"kind": "x"})
    m.gauge_set("bar", 3.5)
    m.observe("lat_seconds", 0.03, buckets=(0.01, 0.1, 1.0))
    m.observe("lat_seconds", 5.0, buckets=(0.01, 0.1, 1.0))
    text = m.render()
    assert '# HELP foo_total a counter' in text
    assert 'foo_total{kind="x"} 2' in text
    assert "bar 3.5" in text
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    # cumulative le buckets must stay monotone and ≤ the +Inf count
    assert 'lat_seconds_bucket{le="1"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 2' in text
    assert "lat_seconds_count 2" in text


def test_phase_aggregate_absorbs_timers():
    from distel_tpu.runtime.instrumentation import (
        PhaseAggregate,
        PhaseTimer,
    )

    agg = PhaseAggregate()
    t = PhaseTimer()
    with t.phase("load"):
        pass
    agg.absorb(t)
    agg.observe("load", 0.5)
    snap = agg.snapshot()
    assert snap["load"]["count"] == 2
    assert snap["load"]["max_s"] >= 0.5


# ------------------------------------------------- graceful SIGTERM spill


def test_cli_serve_sigterm_graceful_spill(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # skip TPU-tunnel registration
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "distel_tpu.cli", "serve",
            "--port", "0", "--spill-dir", str(tmp_path), "--workers", "1",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=repo,
        env=env,
    )
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["serving"] is True
        client = ServeClient(
            f"http://127.0.0.1:{ready['port']}", timeout=240
        )
        oid = client.load(BASE)["id"]
        assert client.subsumers(oid, "A")["subsumers"] == _direct_subsumers(
            [BASE], "A"
        )
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=240)
        assert proc.returncode == 0, err
        last = json.loads(out.strip().splitlines()[-1])
        assert last["shutdown"] == "graceful"
        # the resident closure was spilled through the checkpoint
        # machinery on the way down
        spill = os.path.join(str(tmp_path), f"{oid}.snapshot.npz")
        assert last["spilled"] == [spill]
        assert os.path.exists(spill)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=60)


# ----------------------------------------- warmup precompile accounting


def test_warmup_precompile_makes_same_bucket_load_compile_free(tmp_path):
    """ISSUE 2 satellite: after the startup warmup precompiles a
    bucket, loading a DIFFERENT ontology in that bucket reports
    ``compile_s`` ≈ 0 with a program-cache hit, and the /metrics
    compile counters move accordingly."""
    from distel_tpu.frontend.ontology_tools import synthetic_ontology

    kw = dict(
        n_classes=400, n_anatomy=60, n_locations=40, n_definitions=30
    )
    text_a = synthetic_ontology(seed=7, **kw)
    text_b = synthetic_ontology(seed=99, **kw)
    warm_file = tmp_path / "warm.ofn"
    warm_file.write_text(text_a)
    with serving(warmup_paths=[str(warm_file)]) as (app, client):
        assert app.warmup_wait(600), "warmup thread never finished"
        m0 = client.metrics_text()
        assert _metric(m0, "distel_warmup_programs_total") == 1
        rec = client.load(text_b)
        # the load's increment record carries the compile telemetry
        assert rec["bucket_signature"].startswith("b")
        assert rec["program_cache_hit"] is True
        assert rec["compile_s"] == 0.0
        m1 = client.metrics_text()
        assert _metric(m1, "distel_program_cache_hits_total") >= 1
        health = client.healthz()
        assert health["warmup_done"] is True
