"""Prometheus text-exposition correctness: label-value escaping round
trips (a `"` / `\\` / newline in an ontology id must not corrupt the
page), `relabel_sample` on lines whose values carry structure
characters, and the STRICT exposition parser that guards the router's
aggregated /metrics page against regressions a real scraper would
reject."""

import json
import math

import pytest

from distel_tpu.serve.metrics import (
    Metrics,
    aggregate_expositions,
    escape_help,
    escape_label_value,
    parse_exposition,
    parse_label_block,
    relabel_sample,
    split_sample,
)

NASTY = 'evil"id\\with\nnewline and {braces} and spaces'


# ------------------------------------------------------------- escaping


def test_escape_label_value_spec():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"
    # backslash escapes FIRST: an input already containing \n text
    # stays distinguishable from a real newline
    assert escape_label_value("a\\nb") == "a\\\\nb"


def test_render_escapes_labels_and_help_round_trip():
    m = Metrics()
    m.describe("distel_t_total", "help with \\ backslash\nand newline")
    m.counter_inc("distel_t_total", {"oid": NASTY}, 3.0)
    m.observe("distel_t_seconds", 0.2, {"oid": NASTY}, buckets=(0.1, 1.0))
    page = m.render()
    # one line per sample: the newline in the value must be escaped
    assert not any(
        line.strip() and split_sample(line) is None
        for line in page.splitlines()
        if not line.startswith("#")
    )
    fams = parse_exposition(page)
    name, labels, value = fams["distel_t_total"]["samples"][0]
    assert labels["oid"] == NASTY
    assert value == 3.0
    assert "\\n" in fams["distel_t_total"]["help"]
    hist = fams["distel_t_seconds"]
    assert hist["type"] == "histogram"
    bucket_labels = [
        lb for n, lb, _ in hist["samples"] if n.endswith("_bucket")
    ]
    assert all(lb["oid"] == NASTY for lb in bucket_labels)


def test_escape_help():
    assert escape_help("a\nb\\c") == "a\\nb\\\\c"


# ---------------------------------------------------- sample splitting


def test_split_sample_structure_chars_in_values():
    line = 'm{a="x} y",b="q\\"z"} 5'
    name, block, rest = split_sample(line)
    assert name == "m" and rest == "5"
    labels = parse_label_block(block)
    assert labels == {"a": "x} y", "b": 'q"z'}
    # no labels
    assert split_sample("m 1") == ("m", None, "1")
    # timestamped
    assert split_sample("m{} 1 1700000000000")[2] == "1 1700000000000"
    # junk is not a sample
    assert split_sample("#comment") is None
    assert split_sample('m{a="unterminated 5') is None
    assert split_sample("m") is None


def test_relabel_sample_preserves_nasty_values():
    m = Metrics()
    m.counter_inc("distel_t_total", {"oid": NASTY})
    line = [
        l for l in m.render().splitlines()
        if l.startswith("distel_t_total{")
    ][0]
    out = relabel_sample(line, 'replica="r\\"0"')
    name, block, rest = split_sample(out)
    labels = parse_label_block(block)
    assert labels["oid"] == NASTY
    assert labels["replica"] == 'r"0'
    assert rest == "1"
    # comments and unparseable lines pass through
    assert relabel_sample("# HELP x y", "a=\"1\"") == "# HELP x y"
    assert relabel_sample("", "a=\"1\"") == ""
    # an EMPTY label block must not become '{,replica=...}' — the
    # strict parser (and any real scraper) rejects that
    out = relabel_sample("m{} 1", 'replica="r0"')
    assert out == 'm{replica="r0"} 1'
    parse_exposition(out + "\n")


# ------------------------------------------------------- strict parser


def test_parser_rejects_scraper_poison():
    with pytest.raises(ValueError):  # non-contiguous family
        parse_exposition("a 1\nb 2\na 3\n")
    with pytest.raises(ValueError):  # duplicate TYPE
        parse_exposition("# TYPE a counter\n# TYPE a counter\na 1\n")
    with pytest.raises(ValueError):  # duplicate HELP
        parse_exposition("# HELP a x\n# HELP a y\na 1\n")
    with pytest.raises(ValueError):  # TYPE after samples
        parse_exposition("a 1\n# TYPE a counter\n")
    with pytest.raises(ValueError):  # bad escape in value
        parse_exposition('a{x="\\q"} 1\n')
    with pytest.raises(ValueError):  # bucket without le
        parse_exposition(
            "# TYPE h histogram\nh_bucket 1\nh_sum 1\nh_count 1\n"
        )
    with pytest.raises(ValueError):  # histogram without +Inf
        parse_exposition(
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n'
        )
    with pytest.raises(ValueError):  # not a sample at all
        parse_exposition("!!!\n")
    with pytest.raises(ValueError):  # garbage value
        parse_exposition("a one\n")


def test_parser_accepts_special_values():
    fams = parse_exposition(
        "# TYPE h histogram\n"
        'h_bucket{le="0.1"} 1\nh_bucket{le="+Inf"} 2\n'
        "h_sum 0.3\nh_count 2\n"
        "g +Inf\ng2 -Inf\ng3 NaN\n"
    )
    assert fams["h"]["type"] == "histogram"
    assert fams["g"]["samples"][0][2] == math.inf
    assert fams["g2"]["samples"][0][2] == -math.inf
    assert math.isnan(fams["g3"]["samples"][0][2])


def test_aggregated_exposition_parses_strictly():
    """The satellite guard: merging replica pages (same families, a
    histogram, nasty label values) must yield ONE contiguous group per
    family with a single HELP/TYPE — validated by the strict parser."""
    pages = {}
    for rid in ("r0", "r1"):
        m = Metrics()
        m.describe("distel_req_total", "requests")
        m.counter_inc("distel_req_total", {"oid": NASTY})
        m.describe("distel_lat_seconds", "latency")
        m.observe("distel_lat_seconds", 0.2, buckets=(0.1, 1.0))
        m.gauge_set("distel_depth", 2)
        pages[rid] = m.render()
    agg = aggregate_expositions(pages)
    fams = parse_exposition(agg)
    # every sample carries its replica label, values intact
    samples = fams["distel_req_total"]["samples"]
    assert {lb["replica"] for _, lb, _ in samples} == {"r0", "r1"}
    assert all(lb["oid"] == NASTY for _, lb, _ in samples)
    # histogram suffix samples grouped under the declared family
    hist = fams["distel_lat_seconds"]
    assert hist["type"] == "histogram"
    names = {n for n, _, _ in hist["samples"]}
    assert names == {
        "distel_lat_seconds_bucket",
        "distel_lat_seconds_sum",
        "distel_lat_seconds_count",
    }


def test_serve_app_metrics_page_parses_strictly():
    """A live ServeApp's /metrics (counters + live gauges + frontier
    gauge group + phase summaries) survives the strict parser."""
    from distel_tpu.serve.server import ServeApp

    app = ServeApp(fast_path_min_concepts=0)
    try:
        app.phases.observe("load", 0.1)  # exercise the summary path
        status, ctype, payload = app._ep_metrics(
            query={}, body=b"", deadline_s=None
        )
        assert status == 200
        fams = parse_exposition(payload.decode())
        assert "distel_queue_depth" in fams
        assert fams["distel_request_phase_seconds"]["type"] == "summary"
    finally:
        app.close(final_spill=False)


def test_serve_app_renders_run_family_with_step_rule_gauges():
    """ISSUE 14 satellite: a live ServeApp /metrics page renders the
    run-observatory ``distel_run_*`` family AND the PR 13
    ``distel_step_rule_seconds{rule=}`` labeled gauges together, and
    the whole page still survives the strict exposition parser."""
    import distel_tpu.runtime.instrumentation as instr
    from distel_tpu.obs.ledger import RUN_EVENTS
    from distel_tpu.runtime.instrumentation import StepRuleAggregate
    from distel_tpu.serve.server import ServeApp

    agg = StepRuleAggregate()
    agg.record({"cr1": 0.1, "cr6": 0.4, "embed": 0.05}, source="test")
    old = instr.STEP_RULE_EVENTS
    instr.STEP_RULE_EVENTS = agg
    RUN_EVENTS.begin("expo-run", meta={})
    RUN_EVENTS.update(
        "expo-run", round=7.0, derivation_rate=123.0, eta_s=42.0,
        budget_remaining_s=600.0, stall=1.0,
    )
    app = ServeApp(fast_path_min_concepts=0)
    try:
        status, _ctype, payload = app._ep_metrics(
            query={}, body=b"", deadline_s=None
        )
        assert status == 200
        fams = parse_exposition(payload.decode())
        # the run family, live-sampled from RUN_EVENTS
        assert fams["distel_run_round"]["samples"] == [
            ("distel_run_round", {}, 7.0)
        ]
        assert fams["distel_run_eta_s"]["samples"][0][2] == 42.0
        assert fams["distel_run_budget_remaining_s"]["samples"][0][2] == 600.0
        assert fams["distel_run_stall"]["samples"][0][2] == 1.0
        assert fams["distel_run_derivation_rate"]["type"] == "gauge"
        # ...next to the per-rule step attribution family
        samples = fams["distel_step_rule_seconds"]["samples"]
        assert ("distel_step_rule_seconds", {"rule": "cr6"}, 0.4) in samples
        assert ("distel_step_rule_seconds", {"rule": "other"}, 0.05) in samples
        # /debug/runs serves the same telemetry's per-run summaries
        s, _ct, pl = app._ep_debug_runs(query={}, body=b"", deadline_s=None)
        assert s == 200
        runs = json.loads(pl)["runs"]
        assert any(r["run_id"] == "expo-run" for r in runs)
    finally:
        app.close(final_spill=False)
        instr.STEP_RULE_EVENTS = old
        RUN_EVENTS.end("expo-run", "done")
