"""Adaptive sparse-tail execution (ISSUE 4): the frontier-compacted
step program + dense/sparse controller.

The soundness claim under test: an adaptive run (controller switching
low-density rounds onto the sparse step) is BYTE-IDENTICAL per round to
a dense-only run — same per-round derivation counts, same final S/R
closures — because the sparse tier's active-set selection replicates
the dense step's gating semantics exactly, and rows it skips provably
contribute nothing new under monotone OR.  Plus the tier's ops
properties: workspace overflow falls back to the dense step for the
round (never drops work), and same-capacity sparse programs of
same-bucket ontologies share one executable through the program
registry, like the dense programs."""

import numpy as np
import pytest

from distel_tpu.core.indexing import index_ontology
from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine
from distel_tpu.frontend.normalizer import normalize
from distel_tpu.frontend.ontology_tools import chain_tailed_ontology
from distel_tpu.owl import parser

from test_bucketing import _same_bucket_pair


def _indexed(text):
    return index_ontology(normalize(parser.parse(text)))


@pytest.fixture(scope="module")
def galen_idx():
    """GALEN-shape corpus with a subclass-chain tail appended — late
    rounds derive one chain hop each, the regime the sparse tier is
    for."""
    return _indexed(chain_tailed_ontology(400, 12))


def _observed(idx, sparse, **kw):
    engine = RowPackedSaturationEngine(idx, unroll=1, bucket=True, **kw)
    rounds = []
    res = engine.saturate_observed(
        observer=lambda it, d, ch: rounds.append((it, d, ch)),
        sparse_tail=sparse,
    )
    return engine, rounds, res


def _assert_same_closure(res_a, res_b):
    assert np.array_equal(
        np.asarray(res_a.packed_s), np.asarray(res_b.packed_s)
    )
    assert np.array_equal(
        np.asarray(res_a.packed_r), np.asarray(res_b.packed_r)
    )


# --------------------------------------------- per-round golden parity


def test_adaptive_matches_dense_per_round(galen_idx):
    """THE parity fixture: dense-only vs adaptive observed runs on the
    Galen shape produce identical per-round (iteration, derivations,
    changed) sequences AND byte-identical final closures — with the
    threshold forced high so every post-warmup round runs sparse (the
    strictest exercise of the selection logic)."""
    _, dense_rounds, res_d = _observed(galen_idx, {"enable": False})
    eng, ad_rounds, res_a = _observed(
        galen_idx, {"density_threshold": 1.1, "hysteresis_rounds": 1}
    )
    assert ad_rounds == dense_rounds
    _assert_same_closure(res_d, res_a)
    tiers = [s.tier for s in eng.frontier_rounds]
    assert tiers[0] == "dense"  # all-dirty first round
    assert tiers.count("sparse") >= 3
    # telemetry coherence: round records cover every observed round,
    # densities fall off monotonically to the empty-frontier finish
    assert len(eng.frontier_rounds) == len(ad_rounds)
    assert eng.frontier_rounds[-1].rows_touched == 0
    assert eng.frontier_rounds[-1].density == 0.0


def test_adaptive_default_threshold_runs_sparse_tail(galen_idx):
    """With the DEFAULT controller config the chain tail's low-density
    rounds go sparse (hysteresis honored) and the closure still
    matches dense-only."""
    _, dense_rounds, res_d = _observed(galen_idx, {"enable": False})
    eng, ad_rounds, res_a = _observed(galen_idx, True)
    assert ad_rounds == dense_rounds
    _assert_same_closure(res_d, res_a)
    sts = eng.frontier_rounds
    assert any(s.tier == "sparse" for s in sts)
    # hysteresis: the first below-threshold round stays dense
    thr = RowPackedSaturationEngine._SPARSE_DEFAULTS["density_threshold"]
    first_below = next(i for i, s in enumerate(sts) if s.density < thr)
    assert sts[first_below].tier == "dense"


# -------------------------------------- overflow -> dense fallback


def test_capacity_overflow_falls_back_dense(galen_idx):
    """A one-rung roster with a tiny floor overflows on the busy
    rounds: those run dense (flagged overflow), the tail still runs
    sparse, and the closure is unchanged — overflow delays the tier,
    never drops work."""
    _, dense_rounds, res_d = _observed(galen_idx, {"enable": False})
    eng, ad_rounds, res_a = _observed(
        galen_idx,
        {
            "density_threshold": 1.1,
            "hysteresis_rounds": 1,
            "capacity_buckets": 1,
            "capacity_floor": 8,
        },
    )
    assert ad_rounds == dense_rounds
    _assert_same_closure(res_d, res_a)
    sts = eng.frontier_rounds
    assert any(s.overflow and s.tier == "dense" for s in sts)
    assert any(s.tier == "sparse" for s in sts)


# ------------------------------------- program sharing across buckets


def test_same_bucket_sparse_programs_share_executable():
    """Two same-bucket DIFFERENT ontologies: the second engine's
    sparse-step builds are in-process registry hits for every capacity
    rung the first one compiled — the cold-start story of the dense
    programs, extended to the sparse roster."""
    text_a, text_b = _same_bucket_pair()
    idx_a, idx_b = _indexed(text_a), _indexed(text_b)
    cfg = {"density_threshold": 1.1, "hysteresis_rounds": 1}
    eng_a, _, _ = _observed(idx_a, cfg)
    eng_b, _, _ = _observed(idx_b, cfg)
    assert eng_a.bucket_signature == eng_b.bucket_signature
    assert eng_a._sparse_builds, "run A compiled no sparse programs"
    keys_a = set(eng_a._aot_sparse)
    hits_b = {
        tuple(
            int(x) for x in
            st.program[len("sparse["):-1].split(",")
        ): st.program_cache_hit
        for st in eng_b._sparse_builds
    }
    shared = [k for k in hits_b if k in keys_a]
    assert shared, (keys_a, hits_b)
    assert all(hits_b[k] for k in shared), hits_b


def test_sparse_precompile_warms_floor_rung(galen_idx):
    """precompile()'s default roster includes the sparse tier's
    floor-rung program; a second same-bucket engine then gets it as a
    registry hit."""
    eng = RowPackedSaturationEngine(
        galen_idx, unroll=1, bucket=True, sparse_tail=True
    )
    eng.precompile(programs=("sparse",))
    floor = eng._sparse_cfg["capacity_floor"]
    key = (
        floor,
        floor if eng._scan4 else 0,
        floor if eng._scan6 else 0,
    )
    assert key in eng._aot_sparse
    eng2 = RowPackedSaturationEngine(
        galen_idx, unroll=1, bucket=True, sparse_tail=True
    )
    eng2._sparse_aot(*key)
    assert eng2._sparse_builds[-1].program_cache_hit


# ------------------------- rebind_role_closure dropped-span regression


_ALL_DROPPED_BASE = (
    # the only links ride role q; the only ∃-on-the-left axiom needs r,
    # which no link can satisfy -> its whole scanned span is dropped
    "SubClassOf(A ObjectSomeValuesFrom(q B))\n"
    "SubClassOf(C ObjectSomeValuesFrom(q B))\n"
    "SubClassOf(ObjectSomeValuesFrom(r B) RHit)\n"
    "SubClassOf(A A2)\n"
)


def test_rebind_consumes_persisted_dropped_spans():
    """An all-dropped CR4 table persists its span grid at build;
    rebind under a closure that revives a dropped span must refuse
    (the compiled program lacks the structure)."""
    idx_old = _indexed(_ALL_DROPPED_BASE)
    idx_new = _indexed(_ALL_DROPPED_BASE + "SubObjectPropertyOf(q r)\n")
    assert idx_old.n_roles == idx_new.n_roles
    eng = RowPackedSaturationEngine(idx_old, scan_chunks=True)
    assert eng._scan_mode
    assert eng._scan4 is None  # every span dead at build
    assert eng._scan4_dropped, "build must persist the dropped spans"
    eng.saturate()
    assert not eng.rebind_role_closure(idx_new.role_closure)


def test_degenerate_sparse_cfg_rejected_at_build(galen_idx):
    """capacity_buckets/capacity_floor < 1 or hysteresis_rounds < 1
    must be rejected at engine construction — capacity_buckets=0 used
    to surface rounds deep into saturate_observed as a negative-shift
    ValueError from _sparse_rung, and hysteresis_rounds=0 made the
    controller ignore the density threshold entirely (below >= 0 is
    always true from round 2 on)."""
    for bad in (
        {"capacity_buckets": 0},
        {"capacity_floor": 0},
        {"hysteresis_rounds": 0},
    ):
        with pytest.raises(ValueError, match="sparse_tail"):
            RowPackedSaturationEngine(
                galen_idx, unroll=1, bucket=True, sparse_tail=bad
            )


def test_rebind_dropped_spans_survive_scan_rk_desync():
    """The desync tripwire: rebind must consult the spans PERSISTED by
    build_scan, not re-derive boundaries from self._scan_rk — corrupt
    the latter and the refusal must still come out right (re-deriving
    would divide by a zero chunk size here)."""
    idx_old = _indexed(_ALL_DROPPED_BASE)
    idx_new = _indexed(_ALL_DROPPED_BASE + "SubObjectPropertyOf(q r)\n")
    eng = RowPackedSaturationEngine(idx_old, scan_chunks=True)
    assert eng._scan4 is None
    eng._scan_rk = (0, 0)  # a desynced grid re-derivation would crash
    assert not eng.rebind_role_closure(idx_new.role_closure)
