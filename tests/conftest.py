"""Test config: run JAX on a virtual 8-device CPU mesh, and arm the
runtime lockdep shim for the concurrency suites.

Mirrors the reference's single-host-multi-shard test mode ("minimum of 7
Redis instances ... on the single machine", reference README.md:43): real
GSPMD partitioning, virtual devices.

The recipe itself (env pinning, backend-factory drop, pallas import order)
lives in distel_tpu.testing.cpumesh so the driver's multichip-gate
subprocess (__graft_entry__._dryrun_child) uses the identical code path.
"""

import pytest

from distel_tpu.testing.cpumesh import force_cpu_mesh

force_cpu_mesh(8, exact=True)

#: test modules whose whole point is concurrent locking — they run
#: under the runtime lockdep shim (distel_tpu/testing/lockdep.py): an
#: acquisition-order inversion observed on ANY schedule fails the
#: test, even when this run's interleaving didn't deadlock
_LOCKDEP_MODULES = ("test_serve_concurrency", "test_fleet")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "no_lockdep: opt out of the runtime lockdep shim (for tests "
        "that intentionally seed inversions or contend on raw locks)",
    )
    config.addinivalue_line(
        "markers",
        "slow: opt-in stress/soak harnesses excluded from tier-1 "
        "(`-m 'not slow'`); run explicitly, e.g. "
        "`pytest -m slow tests/test_restore_churn_stress.py`",
    )


@pytest.fixture(autouse=True)
def _lockdep_guard(request):
    mod = getattr(request.node, "module", None)
    name = getattr(mod, "__name__", "")
    if (
        not any(name.endswith(m) for m in _LOCKDEP_MODULES)
        or request.node.get_closest_marker("no_lockdep") is not None
    ):
        yield
        return
    from distel_tpu.testing import lockdep

    lockdep.enable()
    # NO reset here: edges accumulate across the armed modules'
    # tests, so A->B observed in one test and B->A in a later one is
    # still caught as an inversion; check() consumes only the
    # violations, attributing each to the test whose schedule closed
    # the cycle
    try:
        yield
        # fail the test on inversions its schedule didn't deadlock on
        lockdep.check()
    finally:
        lockdep.disable()
