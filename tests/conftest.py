"""Test config: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's single-host-multi-shard test mode ("minimum of 7
Redis instances ... on the single machine", reference README.md:43): real
protocol, colocated shards. Here: real GSPMD partitioning, virtual devices.
Must run before any `import jax`.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
