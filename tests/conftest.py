"""Test config: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's single-host-multi-shard test mode ("minimum of 7
Redis instances ... on the single machine", reference README.md:43): real
GSPMD partitioning, virtual devices.

The environment pre-registers the axon TPU-tunnel plugin at interpreter
start (sitecustomize, keyed on PALLAS_AXON_POOL_IPS) and pins
``jax_platforms="axon,cpu"`` via ``jax.config`` — which an env var cannot
override after the fact.  Tests must never depend on (or hold) the single
real chip, so we force the config back to cpu, drop the non-cpu backend
factories before any backend initializes, and clear the pool var so test
subprocesses never re-register the tunnel either.
"""

import os

_N_DEVICES = 8
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append(f"--xla_force_host_platform_device_count={_N_DEVICES}")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""  # subprocesses: no tunnel registration

import jax  # noqa: E402

# Import pallas while the tpu platform is still registered — its lowering
# registration needs the platform name, and tests exercise the Pallas
# interpreter on CPU.
import jax.experimental.pallas  # noqa: E402,F401

jax.config.update("jax_platforms", "cpu")
try:
    import jax._src.xla_bridge as _xb

    assert not _xb.backends_are_initialized(), (
        "JAX backends initialized before conftest could pin cpu"
    )
    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name != "cpu":
            _xb._backend_factories.pop(_name, None)
except (ImportError, AttributeError):
    # private-API drift tolerated: jax.config.update above suffices alone
    pass

assert len(jax.devices()) == _N_DEVICES, jax.devices()
