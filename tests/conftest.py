"""Test config: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's single-host-multi-shard test mode ("minimum of 7
Redis instances ... on the single machine", reference README.md:43): real
GSPMD partitioning, virtual devices.

The recipe itself (env pinning, backend-factory drop, pallas import order)
lives in distel_tpu.testing.cpumesh so the driver's multichip-gate
subprocess (__graft_entry__._dryrun_child) uses the identical code path.
"""

from distel_tpu.testing.cpumesh import force_cpu_mesh

force_cpu_mesh(8, exact=True)
