"""Packed bitset engine: bit-identical to the dense engine and the CPU
oracle across every rule (CR1-CR6, ⊥, domain/range), plus resume and
classifier integration."""

import numpy as np
import pytest

from distel_tpu.core.engine import SaturationEngine
from distel_tpu.core.indexing import index_ontology
from distel_tpu.core.packed_engine import PackedSaturationEngine
from distel_tpu.frontend.normalizer import normalize
from distel_tpu.frontend.ontology_tools import synthetic_ontology
from distel_tpu.owl import parser
from distel_tpu.testing.differential import diff_engine_vs_oracle

from sharding_support import requires_shard_map

BOTTOM_ONTO = """
SubClassOf(Cat Mammal)
SubClassOf(Mammal Animal)
EquivalentClasses(Feline Cat)
SubClassOf(Cat ObjectSomeValuesFrom(hasParent Cat))
SubClassOf(ObjectSomeValuesFrom(hasParent Animal) Animal)
DisjointClasses(Cat Dog)
SubClassOf(CatDog Cat)
SubClassOf(CatDog Dog)
SubClassOf(Kitten ObjectSomeValuesFrom(hasParent CatDog))
SubObjectPropertyOf(hasParent hasAncestor)
SubObjectPropertyOf(ObjectPropertyChain(hasAncestor hasAncestor) hasAncestor)
ObjectPropertyDomain(hasParent Animal)
ObjectPropertyRange(hasParent Animal)
TransitiveObjectProperty(partOf)
SubClassOf(Paw ObjectSomeValuesFrom(partOf Leg))
SubClassOf(Leg ObjectSomeValuesFrom(partOf Body))
SubClassOf(ObjectSomeValuesFrom(partOf Body) BodyPart)
"""


def _indexed(text):
    norm = normalize(parser.parse(text))
    return norm, index_ontology(norm)


@pytest.fixture(scope="module")
def small():
    return _indexed(BOTTOM_ONTO)


def test_packed_matches_dense_all_rules(small):
    norm, idx = small
    dense = SaturationEngine(idx).saturate()
    packed = PackedSaturationEngine(idx).saturate()
    n, nl = idx.n_concepts, idx.n_links
    assert packed.iterations == dense.iterations
    assert packed.derivations == dense.derivations
    assert (packed.s[:n, :n] == dense.s[:n, :n]).all()
    assert (packed.r[:n, :nl] == dense.r[:n, :nl]).all()
    # ⊥ propagated: Kitten has a CatDog parent, so Kitten is unsat too
    unsat = {idx.concept_names[i] for i in packed.unsatisfiable()}
    assert {"CatDog", "Kitten"} <= unsat


def test_packed_matches_oracle(small):
    norm, idx = small
    report = diff_engine_vs_oracle(norm, PackedSaturationEngine(idx).saturate())
    assert report.ok(), report.summary()


def test_packed_matches_dense_synthetic():
    norm, idx = _indexed(
        synthetic_ontology(
            n_classes=300, n_anatomy=50, n_locations=35, n_definitions=20
        )
    )
    dense = SaturationEngine(idx).saturate()
    packed = PackedSaturationEngine(idx).saturate()
    n = idx.n_concepts
    assert packed.derivations == dense.derivations
    assert (packed.s[:n, :n] == dense.s[:n, :n]).all()


def test_packed_resume_from_snapshot(small):
    norm, idx = small
    eng = PackedSaturationEngine(idx)
    full = eng.saturate()
    # resume from the converged state: zero new derivations, same closure
    again = eng.saturate(initial=(full.s, full.r))
    assert again.derivations == 0
    assert (again.s == full.s).all()


def test_packed_no_links_ontology():
    norm, idx = _indexed("SubClassOf(A B)\nSubClassOf(B C)")
    packed = PackedSaturationEngine(idx).saturate()
    a = idx.concept_ids["A"]
    c = idx.concept_ids["C"]
    assert c in packed.subsumers(a)


def test_packed_nf4_without_links():
    # ∃r.A ⊑ B axioms but no A ⊑ ∃r.B producers: the link table is empty
    # and CR4 can never fire — must construct and run, not crash
    norm, idx = _indexed(
        "SubClassOf(ObjectSomeValuesFrom(hasParent Animal) Animal)\n"
        "SubClassOf(A B)"
    )
    assert idx.n_links == 0 and len(idx.nf4) > 0
    packed = PackedSaturationEngine(idx).saturate()
    assert idx.concept_ids["B"] in packed.subsumers(idx.concept_ids["A"])


def test_classifier_rejects_unknown_engine():
    from distel_tpu.config import ClassifierConfig
    from distel_tpu.runtime.classifier import ELClassifier

    with pytest.raises(ValueError, match="unknown engine"):
        ELClassifier(ClassifierConfig(engine="Packed")).classify_text(
            "SubClassOf(A B)"
        )


def test_classifier_engine_selection():
    from distel_tpu.config import ClassifierConfig
    from distel_tpu.runtime.classifier import ELClassifier

    cfg = ClassifierConfig(engine="packed", use_native_loader=False)
    res = ELClassifier(cfg).classify_text(BOTTOM_ONTO)
    assert "CatDog" in res.taxonomy.unsatisfiable
    cfg2 = ClassifierConfig(engine="auto")  # auto = rowpacked flagship
    res2 = ELClassifier(cfg2).classify_text(BOTTOM_ONTO)
    assert res2.result.derivations == res.result.derivations


# ----------------------------------------------------- mesh-sharded path


@pytest.fixture(scope="module")
def mesh8():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices (see conftest.py)")
    return jax.sharding.Mesh(np.array(jax.devices()[:8]), ("c",))


@requires_shard_map
def test_sharded_packed_matches_local_all_rules(small, mesh8):
    norm, idx = small
    local = PackedSaturationEngine(idx).saturate()
    sharded = PackedSaturationEngine(idx, mesh=mesh8).saturate()
    assert sharded.derivations == local.derivations
    n, nl = idx.n_concepts, idx.n_links
    assert (sharded.s[:n, :n] == local.s[:n, :n]).all()
    assert (sharded.r[:n, :nl] == local.r[:n, :nl]).all()
    report = diff_engine_vs_oracle(norm, sharded)
    assert report.ok(), report.summary()


@requires_shard_map
def test_sharded_packed_synthetic(mesh8):
    norm, idx = _indexed(
        synthetic_ontology(
            n_classes=300, n_anatomy=50, n_locations=35, n_definitions=20
        )
    )
    local = PackedSaturationEngine(idx).saturate()
    sharded = PackedSaturationEngine(idx, mesh=mesh8).saturate()
    assert sharded.derivations == local.derivations
    n = idx.n_concepts
    assert (sharded.s[:n, :n] == local.s[:n, :n]).all()


def test_sharded_packed_state_is_sharded(mesh8):
    norm, idx = _indexed(BOTTOM_ONTO)
    eng = PackedSaturationEngine(idx, mesh=mesh8)
    sp, rp = eng.initial_state()
    assert len(sp.sharding.device_set) == 8
    # each shard holds a [nc/8, wc] row block
    shard_shapes = {s.data.shape for s in sp.addressable_shards}
    assert shard_shapes == {(eng.nc // 8, eng.wc)}


@requires_shard_map
def test_sharded_packed_classifier(mesh8):
    from distel_tpu.config import ClassifierConfig
    from distel_tpu.runtime.classifier import ELClassifier

    cfg = ClassifierConfig(
        engine="packed", mesh_devices=8, use_native_loader=False
    )
    res = ELClassifier(cfg).classify_text(BOTTOM_ONTO)
    assert "CatDog" in res.taxonomy.unsatisfiable
