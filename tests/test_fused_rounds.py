"""Device-resident fused rounds (ISSUE 17): K saturation rounds per
dispatch.

The soundness claim under test: a fused run — ``lax.while_loop`` over
up to K rounds inside ONE device dispatch, tier pick (dense vs sparse)
and convergence test evaluated ON DEVICE from device-resident frontier
stats — retires a per-round (iteration, derivations, changed) sequence
BYTE-IDENTICAL to the per-round adaptive controller's, and lands
byte-identical final closures.  That holds because the fused program's
round body IS the per-round machinery (``_step`` for dense rounds, the
shared ``_sparse_exec`` for sparse rounds) and the device tier test
replicates the host controller's density/hysteresis arithmetic with an
exact integer cutoff; a round whose frontier overflows the traced
sparse capacity rung falls OUT to the host path for that window (never
silently truncates).

Also pinned: K=1 routes through the unchanged per-round controller
(byte-identity is by construction, asserted anyway), and the dispatch
COLLAPSE is real — counted at the jit-call sites by
``DISPATCH_EVENTS``, K rounds retire per device launch instead of one.
"""

import numpy as np
import pytest

from distel_tpu.core.engine import fetch_global
from distel_tpu.core.indexing import index_ontology
from distel_tpu.core.rowpacked_engine import RowPackedSaturationEngine
from distel_tpu.frontend.normalizer import normalize
from distel_tpu.frontend.ontology_tools import chain_tailed_ontology
from distel_tpu.owl import parser

from sharding_support import requires_shard_map


@pytest.fixture(scope="module")
def galen_idx():
    """Chain-tailed GALEN shape — late rounds derive one chain hop
    each, so a run has enough rounds for multiple K=4 windows.  The
    DisjointClasses axiom makes part of the chain unsatisfiable, so the
    engines build with ⊥ present and the fused program's CR5 branch is
    traced and exercised by every parity assertion below."""
    text = chain_tailed_ontology(400, 12)
    text += "\nDisjointClasses(TailChain3 TailChain7)"
    return index_ontology(normalize(parser.parse(text)))


#: forces every post-warmup round sparse — the strictest exercise of
#: the on-device tier pick + compaction (same knob the sparse-tail and
#: sharded parity fixtures use)
_ALL_SPARSE = {"density_threshold": 1.1, "hysteresis_rounds": 1}

#: forces every round dense — the device tier test must agree with the
#: host that nothing ever goes below threshold
_ALL_DENSE = {"density_threshold": 0.0, "hysteresis_rounds": 1}

#: one tiny sparse rung: busy rounds overflow the traced capacity and
#: must fall out of the fused window to the host path for that round
_OVERFLOW = {
    "density_threshold": 1.1,
    "hysteresis_rounds": 1,
    "capacity_buckets": 1,
    "capacity_floor": 8,
}


def _run(idx, *, mesh=None, sparse=True, fused=None, depth=1):
    engine = RowPackedSaturationEngine(
        idx, unroll=1, bucket=True, mesh=mesh
    )
    rounds = []
    res = engine.saturate_observed(
        observer=lambda it, d, ch: rounds.append((it, d, ch)),
        sparse_tail=sparse,
        fused_rounds=fused,
        pipeline={"enable": depth > 1, "depth": depth},
    )
    return engine, rounds, res


def _closure(res):
    return tuple(
        np.asarray(a)
        for a in fetch_global((res.packed_s, res.packed_r))
    )


def _assert_same_closure(res_a, res_b):
    ca, cb = _closure(res_a), _closure(res_b)
    assert np.array_equal(ca[0], cb[0])
    assert np.array_equal(ca[1], cb[1])


def _dispatch_deltas():
    """Before/after snapshot context for the process-global dispatch
    counters."""
    from distel_tpu.runtime.instrumentation import DISPATCH_EVENTS

    before = DISPATCH_EVENTS.snapshot()

    def delta():
        after = DISPATCH_EVENTS.snapshot()
        return {
            k: after[k] - before[k]
            for k in before
            if k != "last_window_rounds"
        }

    return delta


# ------------------------------------------------- K=1 byte-identity


def test_k1_routes_through_per_round_controller(galen_idx):
    """fused.rounds.k=1 is the per-round adaptive controller — same
    retired sequence, same closure, NO fused windows dispatched."""
    _, base_rounds, res_b = _run(galen_idx, sparse=_ALL_SPARSE)
    delta = _dispatch_deltas()
    eng, k1_rounds, res_1 = _run(
        galen_idx, sparse=_ALL_SPARSE, fused={"rounds": 1}
    )
    d = delta()
    assert k1_rounds == base_rounds
    _assert_same_closure(res_b, res_1)
    assert d["fused_windows"] == 0
    # per-round telemetry says per-round: no window ever spans > 1
    assert all(
        st.rounds_in_window == 1 for st in eng.frontier_rounds
    )


def test_k1_dense_and_pipelined_identity(galen_idx):
    """K=1 under the dense-only config and under speculative pipelining
    (depth 3) both match the controller with fusing unconfigured."""
    for sparse, depth in ((_ALL_DENSE, 1), (_ALL_SPARSE, 3)):
        _, base_rounds, res_b = _run(galen_idx, sparse=sparse, depth=depth)
        _, k1_rounds, res_1 = _run(
            galen_idx, sparse=sparse, fused={"rounds": 1}, depth=depth
        )
        assert k1_rounds == base_rounds
        _assert_same_closure(res_b, res_1)


# ------------------------------------ K>1 retired-sequence identity


@pytest.mark.parametrize("k", (2, 4))
def test_fused_sparse_interleave_matches_per_round(galen_idx, k):
    """THE parity fixture: all-sparse fused windows retire the exact
    per-round sequence and closure of the per-round controller, K
    rounds per dispatch."""
    _, base_rounds, res_b = _run(galen_idx, sparse=_ALL_SPARSE)
    delta = _dispatch_deltas()
    eng, f_rounds, res_f = _run(
        galen_idx, sparse=_ALL_SPARSE, fused={"rounds": k}
    )
    d = delta()
    assert f_rounds == base_rounds
    _assert_same_closure(res_b, res_f)
    # the collapse is counted, not inferred: windows actually retired
    # multiple rounds each, and the per-round launches that remain
    # (host replays, window remainders) are far fewer than the
    # per-round controller would have paid
    assert d["fused_windows"] >= 1
    assert d["fused_rounds_retired"] >= d["fused_windows"]
    per_round_launches = d["dense_dispatches"] + d["sparse_dispatches"]
    assert (
        per_round_launches + d["fused_windows"] < len(base_rounds)
    )
    # telemetry: fused-window rounds carry the retired window size
    riws = [st.rounds_in_window for st in eng.frontier_rounds]
    assert max(riws) > 1
    assert len(riws) == len(f_rounds)


@pytest.mark.parametrize("k", (2, 4))
def test_fused_dense_only_matches_per_round(galen_idx, k):
    """Dense-only fused windows (threshold 0 keeps every round dense on
    device) — per-round identity and closure parity."""
    _, base_rounds, res_b = _run(galen_idx, sparse=_ALL_DENSE)
    _, f_rounds, res_f = _run(
        galen_idx, sparse=_ALL_DENSE, fused={"rounds": k}
    )
    assert f_rounds == base_rounds
    _assert_same_closure(res_b, res_f)


def test_fused_overflow_falls_out_to_host(galen_idx):
    """A one-rung tiny-floor roster: busy rounds overflow the traced
    capacity INSIDE the window, the window stops early with the
    overflowing round NOT retired, and the host replays it through the
    full adaptive round (dense fallback) — parity holds, work is never
    dropped."""
    eng_b, base_rounds, res_b = _run(galen_idx, sparse=_OVERFLOW)
    eng, f_rounds, res_f = _run(
        galen_idx, sparse=_OVERFLOW, fused={"rounds": 4}
    )
    assert f_rounds == base_rounds
    _assert_same_closure(res_b, res_f)
    sts = eng.frontier_rounds
    # host-replayed rounds surface as singleton windows; fused windows
    # still retire multi-round batches around them
    assert any(st.rounds_in_window == 1 for st in sts)
    assert any(st.rounds_in_window > 1 for st in sts)
    # the per-round baseline flags overflow on its dense fallbacks;
    # the fused run's replayed rounds are those same rounds
    assert any(st.overflow for st in eng_b.frontier_rounds)


def test_fused_pipelined_matches_per_round(galen_idx):
    """Speculative window dispatch (depth 3): chained fused windows
    retire the same sequence as the synchronous per-round controller."""
    _, base_rounds, res_b = _run(galen_idx, sparse=_ALL_SPARSE)
    _, f_rounds, res_f = _run(
        galen_idx, sparse=_ALL_SPARSE, fused={"rounds": 4}, depth=3
    )
    assert f_rounds == base_rounds
    _assert_same_closure(res_b, res_f)


# -------------------------------------------- K-adaptive terminal window


def test_k1_adaptive_routes_per_round(galen_idx):
    """fused.rounds.adaptive with K=1 is still the per-round adaptive
    controller — identity holds, no fused windows dispatched."""
    _, base_rounds, res_b = _run(galen_idx, sparse=_ALL_SPARSE)
    delta = _dispatch_deltas()
    _, k1_rounds, res_1 = _run(
        galen_idx, sparse=_ALL_SPARSE,
        fused={"rounds": 1, "adaptive": True},
    )
    d = delta()
    assert k1_rounds == base_rounds
    _assert_same_closure(res_b, res_1)
    assert d["fused_windows"] == 0


def test_k_adaptive_no_shrink_without_decay(galen_idx):
    """The chain tail derives a CONSTANT 1/round — no geometric decay,
    so the tail estimator abstains and adaptive K must dispatch plain
    K=4 windows: the retired sequence matches the non-adaptive run
    exactly."""
    eng_f, f_rounds, res_f = _run(
        galen_idx, sparse=_ALL_SPARSE, fused={"rounds": 4}
    )
    eng_a, a_rounds, res_a = _run(
        galen_idx, sparse=_ALL_SPARSE,
        fused={"rounds": 4, "adaptive": True},
    )
    assert a_rounds == f_rounds
    _assert_same_closure(res_f, res_a)
    assert [st.rounds_in_window for st in eng_a.frontier_rounds] == [
        st.rounds_in_window for st in eng_f.frontier_rounds
    ]


def test_k_adaptive_shrinks_windows_byte_identically(
    galen_idx, monkeypatch
):
    """Force the decay signal to claim ~1 round remaining: every
    window shrinks down the ladder to the K=2 floor, and — window size
    only moves window BOUNDARIES — the retired per-round sequence and
    final closure still match the per-round controller byte for
    byte."""
    from distel_tpu.obs import costmodel

    _, base_rounds, res_b = _run(galen_idx, sparse=_ALL_SPARSE)
    monkeypatch.setattr(
        costmodel, "geometric_tail_remaining", lambda deltas: 1
    )
    delta = _dispatch_deltas()
    eng, f_rounds, res_f = _run(
        galen_idx, sparse=_ALL_SPARSE,
        fused={"rounds": 8, "adaptive": True},
    )
    d = delta()
    assert f_rounds == base_rounds
    _assert_same_closure(res_b, res_f)
    assert d["fused_windows"] >= 1
    # the shrink is observable: no window ever retires more than the
    # floor K=2, where the non-adaptive K=8 run retires bigger windows
    riws = [st.rounds_in_window for st in eng.frontier_rounds]
    assert max(riws) <= 2


# ------------------------------------------------------- mesh parity


@pytest.fixture(scope="module")
def _devices():
    import jax

    return jax.devices()


def _mesh(devices, n):
    import jax

    if len(devices) < n:
        pytest.skip(f"needs {n} virtual devices (see conftest.py)")
    return jax.sharding.Mesh(np.array(devices[:n]), ("c",))


@requires_shard_map
@pytest.mark.parametrize("shards", (1, 2, 4))
@pytest.mark.parametrize("k", (2, 4))
def test_fused_mesh_matches_local_per_round(galen_idx, _devices, shards, k):
    """Sharded fused windows (per-round psums inside the device loop,
    only the window-edge fold reaching the host) retire the
    single-device per-round controller's exact sequence and closures
    at 1/2/4 shards."""
    _, base_rounds, res_b = _run(galen_idx, sparse=_ALL_SPARSE)
    _, f_rounds, res_f = _run(
        galen_idx,
        mesh=_mesh(_devices, shards),
        sparse=_ALL_SPARSE,
        fused={"rounds": k},
    )
    assert f_rounds == base_rounds
    _assert_same_closure(res_b, res_f)


@requires_shard_map
def test_fused_mesh_pipelined(galen_idx, _devices):
    """2-shard fused windows under speculative dispatch (depth 2)."""
    _, base_rounds, res_b = _run(galen_idx, sparse=_ALL_SPARSE)
    _, f_rounds, res_f = _run(
        galen_idx,
        mesh=_mesh(_devices, 2),
        sparse=_ALL_SPARSE,
        fused={"rounds": 4},
        depth=2,
    )
    assert f_rounds == base_rounds
    _assert_same_closure(res_b, res_f)


# ------------------------------------------------ config plumbing


def test_fused_config_normalization():
    eng_cfg = RowPackedSaturationEngine._normalize_fused_cfg
    off = {"enable": True, "rounds": 1, "adaptive": False}
    assert eng_cfg(None) == off
    assert eng_cfg(True) == off
    assert eng_cfg(False) is None
    assert eng_cfg({"rounds": 4})["rounds"] == 4
    assert eng_cfg({"rounds": 4})["adaptive"] is False
    assert eng_cfg({"rounds": 4, "adaptive": True})["adaptive"] is True
    assert eng_cfg({"enable": False, "rounds": 4}) is None
    with pytest.raises(ValueError):
        eng_cfg({"rounds": 0})
    with pytest.raises(ValueError):
        eng_cfg({"bogus": 1})


def test_fused_k_ladder():
    """The precompile/farm roster matches what pick_k can dispatch."""
    lad = RowPackedSaturationEngine._fused_k_ladder
    assert lad(8, False) == [8]
    assert lad(8, True) == [8, 4, 2]
    assert lad(4, True) == [4, 2]
    assert lad(2, True) == [2]
    assert lad(1, True) == [1]


def test_fused_config_reaches_engine_through_make_engine(
    galen_idx, tmp_path
):
    from distel_tpu.config import ClassifierConfig
    from distel_tpu.runtime.classifier import make_engine

    props = tmp_path / "distel.properties"
    props.write_text(
        "fused.rounds.enable = true\nfused.rounds.k = 4\n"
        "fused.rounds.adaptive = true\n"
    )
    cfg = ClassifierConfig.from_properties(str(props))
    assert cfg.fused_rounds_config() == {
        "enable": True, "rounds": 4, "adaptive": True,
    }
    engine = make_engine(cfg, galen_idx)
    assert engine._fused_cfg == {
        "enable": True, "rounds": 4, "adaptive": True,
    }
    props.write_text("fused.rounds.enable = false\n")
    off = ClassifierConfig.from_properties(str(props))
    assert off.fused_rounds_config() is None
